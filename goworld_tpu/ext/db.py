"""Async document-DB helpers for game code.

Reference parity: ``ext/db/gwmongo`` + ``ext/db/gwredis`` — thin wrappers
that run driver calls on a dedicated serial async job group and post
callbacks back to the game loop (gwmongo.go:31-346, gwredis.go:16-44).

No DB drivers ship in this image, so all three helpers are real and
driver-free: :class:`DocDB` over sqlite (one table per collection, JSON
documents, indexable id), :class:`GwRedis` over the in-repo RESP2 client
(netutil/resp.py) and :class:`GwMongo` over the in-repo OP_MSG client
(netutil/mongo.py). Every method is fire-and-forget with
``callback(result, err)`` marshalled back to the main loop via the async
job group, matching gwmongo/gwredis call shapes.
"""

from __future__ import annotations

import json
import os
import sqlite3
from typing import Any, Callable, Optional

from goworld_tpu.utils import async_jobs

_ASYNC_JOB_GROUP = "_docdb"

AsyncCallback = Optional[Callable[[Any, Optional[Exception]], None]]


class DocDB:
    """Sqlite-backed document store with gwmongo's async call shape."""

    def __init__(self) -> None:
        self._conn: sqlite3.Connection | None = None
        self._path: str | None = None
        # Per-instance serial worker: one slow scan on this DB must not
        # stall operations on an unrelated DocDB.
        self._group = f"{_ASYNC_JOB_GROUP}:{id(self)}"

    # --- connection (gwmongo.go:31-70) --------------------------------------

    def dial(self, path: str, callback: AsyncCallback = None) -> None:
        def routine():
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._conn = sqlite3.connect(path, check_same_thread=False)
            self._path = path
            return self

        self._submit(routine, callback)

    def close(self, callback: AsyncCallback = None) -> None:
        def routine():
            if self._conn is not None:
                self._conn.close()
                self._conn = None

        self._submit(routine, callback)

    # --- internals ----------------------------------------------------------

    def _submit(self, routine: Callable, callback: AsyncCallback) -> None:
        async_jobs.append_job(self._group, routine, callback)

    def _table(self, collection: str) -> str:
        if not collection.replace("_", "").isalnum():
            raise ValueError(f"bad collection name {collection!r}")
        if self._conn is None:
            raise RuntimeError("not connected (dial first)")
        self._conn.execute(
            f"CREATE TABLE IF NOT EXISTS c_{collection} "
            "(id TEXT PRIMARY KEY, doc TEXT NOT NULL)"
        )
        return f"c_{collection}"

    @staticmethod
    def _matches(doc: dict, query: dict) -> bool:
        return all(doc.get(k) == v for k, v in query.items())

    def _iter_docs(self, collection: str):
        t = self._table(collection)
        for rid, raw in self._conn.execute(f"SELECT id, doc FROM {t}"):
            yield rid, json.loads(raw)

    # --- queries (gwmongo.go:84-146) ----------------------------------------

    def find_id(self, collection: str, doc_id: str, callback: AsyncCallback) -> None:
        def routine():
            t = self._table(collection)
            row = self._conn.execute(
                f"SELECT doc FROM {t} WHERE id=?", (doc_id,)
            ).fetchone()
            return json.loads(row[0]) if row else None

        self._submit(routine, callback)

    def find_one(self, collection: str, query: dict, callback: AsyncCallback) -> None:
        def routine():
            for rid, doc in self._iter_docs(collection):
                if self._matches(doc, query):
                    return {"_id": rid, **doc}
            return None

        self._submit(routine, callback)

    def find_all(self, collection: str, query: dict, callback: AsyncCallback) -> None:
        def routine():
            return [{"_id": rid, **doc} for rid, doc in self._iter_docs(collection)
                    if self._matches(doc, query)]

        self._submit(routine, callback)

    def count(self, collection: str, query: dict, callback: AsyncCallback) -> None:
        def routine():
            return sum(1 for _, doc in self._iter_docs(collection)
                       if self._matches(doc, query))

        self._submit(routine, callback)

    # --- writes (gwmongo.go:148-283) ----------------------------------------

    def insert(self, collection: str, doc_id: str, doc: dict,
               callback: AsyncCallback = None) -> None:
        def routine():
            t = self._table(collection)
            self._conn.execute(
                f"INSERT INTO {t} (id, doc) VALUES (?, ?)", (doc_id, json.dumps(doc))
            )
            self._conn.commit()

        self._submit(routine, callback)

    def upsert_id(self, collection: str, doc_id: str, doc: dict,
                  callback: AsyncCallback = None) -> None:
        def routine():
            t = self._table(collection)
            self._conn.execute(
                f"INSERT INTO {t} (id, doc) VALUES (?, ?) "
                "ON CONFLICT(id) DO UPDATE SET doc=excluded.doc",
                (doc_id, json.dumps(doc)),
            )
            self._conn.commit()

        self._submit(routine, callback)

    def update_id(self, collection: str, doc_id: str, fields: dict,
                  callback: AsyncCallback = None) -> None:
        def routine():
            t = self._table(collection)
            row = self._conn.execute(
                f"SELECT doc FROM {t} WHERE id=?", (doc_id,)
            ).fetchone()
            if row is None:
                raise KeyError(f"{collection}/{doc_id} not found")
            doc = json.loads(row[0])
            doc.update(fields)
            self._conn.execute(
                f"UPDATE {t} SET doc=? WHERE id=?", (json.dumps(doc), doc_id)
            )
            self._conn.commit()

        self._submit(routine, callback)

    def remove_id(self, collection: str, doc_id: str,
                  callback: AsyncCallback = None) -> None:
        def routine():
            t = self._table(collection)
            n = self._conn.execute(f"DELETE FROM {t} WHERE id=?", (doc_id,)).rowcount
            self._conn.commit()
            if n == 0:
                raise KeyError(f"{collection}/{doc_id} not found")

        self._submit(routine, callback)

    def remove_all(self, collection: str, query: dict,
                   callback: AsyncCallback = None) -> None:
        def routine():
            t = self._table(collection)
            removed = 0
            for rid, doc in list(self._iter_docs(collection)):
                if self._matches(doc, query):
                    self._conn.execute(f"DELETE FROM {t} WHERE id=?", (rid,))
                    removed += 1
            self._conn.commit()
            return removed

        self._submit(routine, callback)

    def drop_collection(self, collection: str, callback: AsyncCallback = None) -> None:
        def routine():
            t = self._table(collection)
            self._conn.execute(f"DROP TABLE {t}")
            self._conn.commit()

        self._submit(routine, callback)


class GwMongo:
    """Async mongo helper over the in-repo OP_MSG client (gwmongo.go:31-346
    call shape): every call runs on a serial worker and posts
    ``callback(result, err)`` back to the game loop."""

    def __init__(self, dbname: str) -> None:
        self._client = None
        self._db = dbname
        self._group = f"{_ASYNC_JOB_GROUP}:mongo:{id(self)}"

    def _submit(self, routine: Callable, callback: AsyncCallback) -> None:
        async_jobs.append_job(self._group, routine, callback)

    def dial(self, url: str, callback: AsyncCallback = None) -> None:
        from goworld_tpu.netutil.mongo import MongoClient, parse_mongo_url

        def routine():
            self._client = MongoClient(**parse_mongo_url(url))
            self._client.ping()
            return self

        self._submit(routine, callback)

    def insert(self, coll: str, doc: dict, callback: AsyncCallback = None) -> None:
        self._submit(lambda: self._client.insert(self._db, coll, [doc]), callback)

    def upsert_id(self, coll: str, _id: str, doc: dict,
                  callback: AsyncCallback = None) -> None:
        doc = dict(doc, _id=_id)
        self._submit(
            lambda: self._client.upsert(self._db, coll, {"_id": _id}, doc),
            callback,
        )

    def find_id(self, coll: str, _id: str, callback: AsyncCallback = None) -> None:
        self._submit(
            lambda: self._client.find_one(self._db, coll, {"_id": _id}), callback
        )

    def find_one(self, coll: str, query: dict, callback: AsyncCallback = None) -> None:
        self._submit(
            lambda: self._client.find_one(self._db, coll, query), callback
        )

    def find_all(self, coll: str, query: dict, callback: AsyncCallback = None) -> None:
        self._submit(lambda: self._client.find(self._db, coll, query), callback)

    def remove_id(self, coll: str, _id: str, callback: AsyncCallback = None) -> None:
        self._submit(
            lambda: self._client.delete(self._db, coll, {"_id": _id}), callback
        )

    def command(self, command: dict, callback: AsyncCallback = None) -> None:
        self._submit(lambda: self._client.command(self._db, command), callback)

    def close(self, callback: AsyncCallback = None) -> None:
        def routine():
            if self._client is not None:
                self._client.close()
                self._client = None

        self._submit(routine, callback)


def dial_mongo(url: str, dbname: str, callback: AsyncCallback = None) -> GwMongo:
    """Connect a :class:`GwMongo` (async; callback fires on the game loop
    with (client, err) — gwmongo.go dial shape)."""
    m = GwMongo(dbname)
    m.dial(url, callback)
    return m


class GwRedis:
    """Async redis helper over the in-repo RESP2 client (gwredis.go:16-44):
    every call runs on a serial worker and posts ``callback(result, err)``
    back to the game loop."""

    def __init__(self) -> None:
        self._client = None
        self._group = f"{_ASYNC_JOB_GROUP}:redis:{id(self)}"

    def _submit(self, routine: Callable, callback: AsyncCallback) -> None:
        async_jobs.append_job(self._group, routine, callback)

    def dial(self, url: str, callback: AsyncCallback = None) -> None:
        from goworld_tpu.netutil.resp import RespClient, parse_redis_url

        def routine():
            self._client = RespClient(**parse_redis_url(url))
            self._client.ping()
            return self

        self._submit(routine, callback)

    def command(self, *args, callback: AsyncCallback = None) -> None:
        """Run any redis command (gwredis exposes the raw Do)."""
        self._submit(lambda: self._client.execute(*args), callback)

    def get(self, key: str, callback: AsyncCallback = None) -> None:
        self._submit(lambda: self._client.get(key), callback)

    def set(self, key: str, val: str, callback: AsyncCallback = None) -> None:
        self._submit(lambda: self._client.set(key, val), callback)

    def delete(self, key: str, callback: AsyncCallback = None) -> None:
        self._submit(lambda: self._client.delete(key), callback)

    def close(self, callback: AsyncCallback = None) -> None:
        def routine():
            if self._client is not None:
                self._client.close()
                self._client = None

        self._submit(routine, callback)


def dial_redis(url: str, callback: AsyncCallback = None) -> GwRedis:
    """Connect a :class:`GwRedis` (async; callback fires on the game loop
    with (client, err) — gwredis.go dial shape)."""
    r = GwRedis()
    r.dial(url, callback)
    return r

"""Extensions built on the public facade (reference ``ext/``)."""

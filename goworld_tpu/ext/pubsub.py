"""Publish/Subscribe service entity with wildcard suffix subscriptions.

Reference parity: ``ext/pubsub/PublishSubscribeService.go:11-264`` —

- ``Subscribe(eid, subject)``: ``subject`` may end with ``*`` matching any
  zero-or-more suffix ("apple.*" receives "apple.", "apple.1", ...); '*' is
  only legal at the end.
- ``Publish(subject, content)``: fires exact subscribers of ``subject`` plus
  wildcard subscribers of every prefix; subscribers receive
  ``OnPublish(subject, content)``.
- ``UnsubscribeAll(eid)`` drops every subscription of one entity.
- Freeze/restore round-trips the subscription tables through entity attrs
  (OnFreeze/OnRestored, :221-264).

The reference walks a ternary-search-trie per character; prefix-keyed hash
maps give the same O(len(subject)) publish with simpler code.
"""

from __future__ import annotations

from goworld_tpu.entity.entity import Entity
from goworld_tpu.utils import gwlog

SERVICE_NAME = "PublishSubscribeService"


class PublishSubscribeService(Entity):
    """The pubsub service entity; shard by subject via call_service_shard_key."""

    @classmethod
    def describe_entity_type(cls, desc):
        desc.define_attr("subscribers", "Persistent")
        desc.define_attr("wildcardSubscribers", "Persistent")

    def on_init(self):
        self._exact: dict[str, set[str]] = {}  # subject → subscriber eids
        self._wildcard: dict[str, set[str]] = {}  # prefix → subscriber eids
        self._by_entity: dict[str, set[str]] = {}  # eid → exact subjects
        self._by_entity_wild: dict[str, set[str]] = {}  # eid → wildcard prefixes

    def on_created(self):
        if not self.attrs.get("subscribers"):
            self.attrs.set("subscribers", {})
        if not self.attrs.get("wildcardSubscribers"):
            self.attrs.set("wildcardSubscribers", {})

    # --- RPC API (service entity methods) -----------------------------------

    def Publish(self, subject: str, content) -> None:
        if "*" in subject:
            gwlog.errorf("pubsub: subject must not contain '*' when publishing: %r", subject)
            return
        targets: set[str] = set()
        targets |= self._exact.get(subject, set())
        for i in range(len(subject) + 1):
            targets |= self._wildcard.get(subject[:i], set())
        gwlog.debugf("%s publish %r -> %d targets", self, subject, len(targets))
        for eid in targets:
            self.call(eid, "OnPublish", subject, content)

    def Subscribe(self, subscriber: str, subject: str) -> None:
        subject, wildcard = self._split_wildcard(subject)
        if subject is None:
            return
        self._subscribe(subscriber, subject, wildcard)

    def Unsubscribe(self, subscriber: str, subject: str) -> None:
        subject, wildcard = self._split_wildcard(subject)
        if subject is None:
            return
        self._unsubscribe(subscriber, subject, wildcard)

    def UnsubscribeAll(self, subscriber: str) -> None:
        for subject in self._by_entity.pop(subscriber, set()):
            subs = self._exact.get(subject)
            if subs is not None:
                subs.discard(subscriber)
                if not subs:
                    del self._exact[subject]
        for prefix in self._by_entity_wild.pop(subscriber, set()):
            subs = self._wildcard.get(prefix)
            if subs is not None:
                subs.discard(subscriber)
                if not subs:
                    del self._wildcard[prefix]

    # --- internals -----------------------------------------------------------

    @staticmethod
    def _split_wildcard(subject: str) -> tuple[str | None, bool]:
        if "*" in subject[:-1]:
            gwlog.errorf("pubsub: '*' only legal at the end of subject: %r", subject)
            return None, False
        if subject.endswith("*"):
            return subject[:-1], True
        return subject, False

    def _subscribe(self, eid: str, subject: str, wildcard: bool) -> None:
        gwlog.debugf("%s subscribe %s -> %r (wildcard=%s)", self, eid, subject, wildcard)
        if wildcard:
            self._wildcard.setdefault(subject, set()).add(eid)
            self._by_entity_wild.setdefault(eid, set()).add(subject)
        else:
            self._exact.setdefault(subject, set()).add(eid)
            self._by_entity.setdefault(eid, set()).add(subject)

    def _unsubscribe(self, eid: str, subject: str, wildcard: bool) -> None:
        table = self._wildcard if wildcard else self._exact
        index = self._by_entity_wild if wildcard else self._by_entity
        subs = table.get(subject)
        if subs is not None:
            subs.discard(eid)
            if not subs:  # drop emptied subjects: subject churn must not leak
                del table[subject]
        owned = index.get(eid)
        if owned is not None:
            owned.discard(subject)
            if not owned:
                del index[eid]

    # --- freeze / restore (PublishSubscribeService.go:221-264) ---------------

    def on_freeze(self):
        self.attrs.set(
            "subscribers",
            {s: {eid: 1 for eid in eids} for s, eids in self._exact.items() if eids},
        )
        self.attrs.set(
            "wildcardSubscribers",
            {s: {eid: 1 for eid in eids} for s, eids in self._wildcard.items() if eids},
        )

    def on_restored(self):
        n = 0
        subs = self.attrs.get("subscribers")
        if subs:
            for subject, eids in subs.to_dict().items():
                for eid in eids:
                    self._subscribe(eid, subject, False)
                    n += 1
        wild = self.attrs.get("wildcardSubscribers")
        if wild:
            for subject, eids in wild.to_dict().items():
                for eid in eids:
                    self._subscribe(eid, subject, True)
                    n += 1
        gwlog.infof("%s: restored %d subscribings", self, n)


def register_service(shard_count: int = 1) -> None:
    """Register the pubsub service (PublishSubscribeService.go:64-66)."""
    from goworld_tpu import service

    service.register_service(PublishSubscribeService, shard_count, SERVICE_NAME)


# --- client-side helpers (subject-sharded routing) ---------------------------


def publish(subject: str, content) -> None:
    from goworld_tpu import service

    service.call_service_shard_key(SERVICE_NAME, subject, "Publish", subject, content)


def subscribe(subscriber_eid: str, subject: str) -> None:
    """Exact subjects shard by the subject string (test_game/Avatar.go:54);
    wildcard subscriptions fan out to EVERY shard so they match publishes of
    any concrete subject regardless of which shard the publish hashes to.
    (The reference inherits a miss here: "foo*" hashed to one shard can miss
    "foo1" published to another; fanning out the rare wildcard subscribe
    fixes that without changing publish-side routing.)"""
    from goworld_tpu import service

    if subject.endswith("*"):
        service.call_service_all(SERVICE_NAME, "Subscribe", subscriber_eid, subject)
    else:
        service.call_service_shard_key(SERVICE_NAME, subject, "Subscribe", subscriber_eid, subject)


def unsubscribe(subscriber_eid: str, subject: str) -> None:
    from goworld_tpu import service

    if subject.endswith("*"):
        service.call_service_all(SERVICE_NAME, "Unsubscribe", subscriber_eid, subject)
    else:
        service.call_service_shard_key(SERVICE_NAME, subject, "Unsubscribe", subscriber_eid, subject)


def unsubscribe_all(subscriber_eid: str) -> None:  # gwlint: keep — reference API (Avatar.go:179)
    """Drop the subscriber from every shard (test_game/Avatar.go:179)."""
    from goworld_tpu import service

    service.call_service_all(SERVICE_NAME, "UnsubscribeAll", subscriber_eid)

"""GameService: packet handling + tick loop + terminate/freeze paths.

Reference parity: ``components/game/GameService.go`` — the main loop
(:76-187) selects {packet queue | 5 ms ticker}; ~20 message handlers
(:92-157); terminate saves + destroys all entities (:194-213); freeze packs
every entity to ``game<N>_freezed.dat`` (:217-266, restore.go:12-34).
``components/game/game.go`` — boot sequence (:66-136) and signal handling
(:138-194). ``lbc/gamelbc.go:17-39`` — CPU% reports to every dispatcher.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import time
from typing import Optional

from goworld_tpu import consts, dispatchercluster, kvdb, kvreg, storage, telemetry
from goworld_tpu.dispatchercluster.cluster import ClusterClient
from goworld_tpu.entity import entity_manager
from goworld_tpu.entity.game_client import GameClient
from goworld_tpu.netutil.packet import Packet
from goworld_tpu.proto.conn import unpack_sync_records
from goworld_tpu.proto.msgtypes import MsgType
from goworld_tpu.telemetry import tracing
from goworld_tpu.utils import async_jobs, crontab, gwlog, gwutils, post

# Sync fan-out per-hop attribution (shared family with the dispatcher's
# dispatcher_route and the gate's gate_demux/client_write hops; bench.py
# --fanout reads the deltas into per-hop shares). The game side is split
# into game_collect + game_pack (entity_manager.collect_entity_sync_infos
# owns both compute sub-hops) and game_send — the per-gate dispatcher-link
# writes below, kept separate so pack COMPUTE is attributable apart from
# wire work (mirroring the gate's gate_demux vs client_write split).
_HOP_GAME_SEND = telemetry.counter(
    "fanout_hop_seconds_total",
    "Busy wall seconds per sync fan-out hop (game_collect|game_pack|"
    "game_send|dispatcher_route|gate_demux|client_write).",
    ("hop",)).labels("game_send")

# run states (GameService.go rsRunning/rsTerminating/rsFreezing...)
RS_RUNNING = 0
RS_TERMINATING = 1
RS_FREEZING = 2
RS_TERMINATED = 3
RS_FREEZED = 4


def freeze_filename(gameid: int) -> str:
    return f"game{gameid}_freezed.dat"


def apply_compilation_cache(value: str) -> Optional[str]:
    """Point jax's persistent XLA compilation cache at ``value`` ([aoi]
    compilation_cache: "auto" = <cwd>/.goworld_jax_cache, "off" = None).

    The payoff is the freeze->restore respawn: the restarted process
    would otherwise re-run every step-jit compile inside the 5 s window
    buffered client RPCs are waiting out; with the cache it LOADS the
    executables compiled at original boot (measured 6.0 s -> 2.5 s
    boot-to-warm on the verify rig). Returns the resolved directory."""
    if value == "off":
        return None
    import jax

    cache = (os.path.join(os.getcwd(), ".goworld_jax_cache")
             if value == "auto" else value)
    os.makedirs(cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    try:
        # jax latches "no cache" on the first compile; if ANYTHING
        # compiled before this config landed (warmup ordering drift, test
        # harnesses), the new dir would be silently ignored without a
        # reset. Private API, so best-effort.
        from jax._src import compilation_cache

        compilation_cache.reset_cache()
    except Exception:  # pragma: no cover - jax-internal drift
        pass
    return cache


class GameService:
    """One game process. Construct, then ``await service.run_async()``."""

    def __init__(self, gameid: int, cfg=None, restore: bool = False) -> None:
        from goworld_tpu.config import get as get_config

        self.gameid = gameid
        self.cfg = cfg or get_config()
        self.restore = restore
        self.run_state = RS_RUNNING
        self.online_games: set[int] = set()
        self.deployment_ready = False
        self._queue: asyncio.Queue = asyncio.Queue()
        self.cluster: Optional[ClusterClient] = None
        self._freeze_acks = 0
        self._stop_event = asyncio.Event()
        self.exit_code: Optional[int] = None
        self._last_sync_collect = 0.0
        self._last_aoi_tick = 0.0
        self._aoi_wedge_warned = False
        self._last_packet_at = 0.0
        self._freeze_acked_at = 0.0
        self._freeze_started_at = 0.0
        # Migrate-in volume counters (gwvar MigrateIn*): a soak whose game
        # RSS climbs names its per-payload cost here.
        self._migrate_in_count = 0
        self._migrate_in_bytes = 0
        self._migrate_in_max = 0
        # Rebalance execution (rebalance/migrator.py): drives dispatcher-
        # commanded migrations with deadline + rollback; ticked from the
        # main loop's entity_logic phase (zero cost while idle).
        rbcfg = getattr(self.cfg, "rebalance", None)
        from goworld_tpu.rebalance import RebalanceMigrator

        self.migrator = RebalanceMigrator(
            migrate_timeout=rbcfg.migrate_timeout if rbcfg else 5.0,
            cooldown=rbcfg.cooldown if rbcfg else 5.0)
        self._report_interval = rbcfg.report_interval if rbcfg else 1.0
        # CPU% over the last report interval (rebalance/report.py reads it).
        self.last_cpu_pct = 0.0
        game_cfg = self.cfg.games.get(gameid)
        self.boot_entity = game_cfg.boot_entity if game_cfg else ""
        self.position_sync_interval = (
            game_cfg.position_sync_interval if game_cfg else consts.POSITION_SYNC_INTERVAL
        )
        self._started_at = 0.0
        # Slow-tick flight recorder ([telemetry] knobs; tracing.py): every
        # tick records its phase budget; /flight serves the ring.
        tcfg = getattr(self.cfg, "telemetry", None)
        self.flight = tracing.FlightRecorder(
            capacity=tcfg.flight_ring_size if tcfg else 240,
            slow_budget=tcfg.slow_tick_budget if tcfg else
            consts.SLOW_TICK_BUDGET,
        )
        # trace_id of the first sampled packet handled in the current tick
        # (0 = untraced tick): gates phase-span emission at commit.
        self._tick_trace_id = 0

    # --- boot (game.go:66-136) ---------------------------------------------

    async def run_async(self) -> int:
        """Full process lifecycle; returns the exit code (0 normal, 2 freeze —
        the CLI restarts freezed games with -restore)."""
        rt = entity_manager.runtime
        rt.gameid = self.gameid
        rt.game_service = self
        self._started_at = time.monotonic()
        tcfg = getattr(self.cfg, "telemetry", None)
        if tcfg is not None:
            tracing.configure_from_config(tcfg)
        tracing.set_flight_recorder(self.flight)
        game_cfg = self.cfg.games.get(self.gameid)
        if game_cfg is not None:
            rt.save_interval = game_cfg.save_interval
            rt.position_sync_interval = game_cfg.position_sync_interval
        if self.cfg.aoi.backend != "auto":
            rt.aoi_backend = "xzlist" if self.cfg.aoi.backend == "xzlist" else "batched"
        # [aoi] capacity/cell/mesh knobs → engine params (ini is the single
        # source of truth; tests may pre-seed rt.aoi_params to override).
        rt.aoi_mesh_shards = max(1, self.cfg.aoi.mesh_shards)
        rt.aoi_shard_mode = self.cfg.aoi.shard_mode
        rt.aoi_strip_placement = self.cfg.aoi.strip_placement
        rt.aoi_pallas_strip_cols = self.cfg.aoi.pallas_strip_cols
        rt.aoi_pallas_inkernel_drain = self.cfg.aoi.pallas_inkernel_drain
        rt.aoi_delivery = self.cfg.aoi.delivery
        rt.aoi_sync_wait_budget = self.cfg.aoi.sync_wait_budget
        rt.aoi_fuse_logic = self.cfg.aoi.fuse_logic
        ecfg = getattr(self.cfg, "entity", None)
        if ecfg is not None:
            # Pre-size the slab store ([entity] slab_initial) so steady-
            # state populations don't pay growth reallocation mid-login.
            rt.slabs.ensure_capacity(ecfg.slab_initial)
        sycfg = getattr(self.cfg, "sync", None)
        if sycfg is not None:
            # [sync] adaptive per-client sync: cadence tiers + delta/
            # quantized records (entity/slabs.py; defaults = legacy path).
            rt.slabs.configure_sync(sycfg)
        if rt.aoi_backend != "xzlist" and rt.aoi_params is None:
            from goworld_tpu.entity.aoi.batched import params_from_config

            rt.aoi_params = params_from_config(self.cfg.aoi)
        if rt.aoi_backend != "xzlist":
            # Per-game aoi_platform overrides the global [aoi] platform: on
            # single-client TPU transports exactly one game may hold the
            # chip (read_config.py GameConfig.aoi_platform).
            platform = (
                (game_cfg.aoi_platform if game_cfg else "")
                or self.cfg.aoi.platform
            )
            if platform == "cpu":
                # Must happen before the first jax use: the TPU plugin
                # ignores JAX_PLATFORMS, so only jax.config reliably keeps a
                # CPU-deploy game process off the chip (read_config.py).
                # ("tpu"/"auto" leave jax's default, which prefers the chip.)
                import jax

                jax.config.update("jax_platforms", "cpu")
            # Persistent XLA compilation cache — the respawn-path fix
            # (apply_compilation_cache docstring).
            apply_compilation_cache(self.cfg.aoi.compilation_cache)
            if self.cfg.aoi.multihost_coordinator:
                # DCN tier: every game joins ONE jax.distributed mesh;
                # process_id is this game's rank among the configured games
                # (read_config validates processes == len(games)). Must run
                # before any other jax use; blocks until every game is up
                # (the CLI spawns the game batch before waiting on tags).
                from goworld_tpu.parallel.multihost import init_multihost

                games_sorted = sorted(self.cfg.games)
                pid = games_sorted.index(self.gameid)
                nprocs = len(games_sorted)
                gwlog.infof(
                    "game %d joining AOI multihost mesh as process %d/%d "
                    "via %s", self.gameid, pid, nprocs,
                    self.cfg.aoi.multihost_coordinator,
                )
                init_multihost(
                    self.cfg.aoi.multihost_coordinator, nprocs, pid
                )
                rt.aoi_multihost = True
                import jax

                gwlog.infof(
                    "game %d AOI multihost mesh joined: %d processes, "
                    "%d global devices", self.gameid, jax.process_count(),
                    jax.device_count(),
                )
            # Compile the engine BEFORE the ready barrier admits clients —
            # the first dispatch otherwise freezes the loop for the whole
            # jit compile (seconds) right as the first clients log in.
            rt.get_aoi_service().warmup()
        if not storage.initialized():
            storage.initialize(self.cfg.storage)
        rt.storage = storage.SyncStorageAdapter()
        if not kvdb.initialized():
            kvdb.initialize(self.cfg.kvdb)

        rbcfg = getattr(self.cfg, "rebalance", None)
        if rbcfg is not None and rbcfg.enabled and rbcfg.planner_service:
            # Planner failover (ISSUE 18): host planning in a sharded
            # service entity — every game registers the type, exactly one
            # wins the kvreg shard race and plans; survivors re-claim the
            # shard when the host dies. Must happen before restore: a
            # frozen planner entity needs its type in the registry.
            from goworld_tpu.rebalance import planner_service

            planner_service.register()

        if self.restore:
            self._restore_freezed_entities()
            # Pre-warm the per-class batched tick jits at the restored
            # populations BEFORE the cluster re-handshake admits traffic:
            # columnar_tick/vmapped_position_tick compile lazily on first
            # call and specialize on the view length, so without this the
            # first live tick after respawn pays the XLA trace while
            # buffered client RPCs are already draining — the ~4.7 s stall
            # vs the 5 s strict RPC timeout ISSUE 7 measured. With
            # [aoi] fuse_logic this also compiles the FUSED step jit for
            # the restored program set (service.prewarm_fused), so the
            # first post-restore fused dispatch adds no fresh trace.
            # (The AOI engine itself is already hot: warmup() ran above,
            # and any tier growth during restore compiled synchronously
            # here too.)
            rt.slabs.prewarm_tick_hooks()
        elif entity_manager.get_nil_space() is None:
            entity_manager.create_nil_space(self.gameid)

        from goworld_tpu.dispatchercluster.cluster import (
            cluster_knobs,
            dispatcher_addrs,
        )

        self.cluster = ClusterClient(
            dispatcher_addrs(self.cfg), self._handshake, self._on_packet,
            self._on_dispatcher_disconnect, **cluster_knobs(self.cfg)
        )
        dispatchercluster.set_cluster(self.cluster)
        self.cluster.start()

        from goworld_tpu import service as service_mod

        service_mod.setup(self.gameid)  # service.go:78-81
        self._install_signal_handlers()
        from goworld_tpu.utils import gwvar
        from goworld_tpu.utils.debug_http import setup_http_server

        lbc_task = None
        debug_srv = None
        hist_writer = None
        hist_task = None
        try:
            # Debug HTTP server (binutil.SetupHTTPServer; game.go:107) + gwvar.
            gwvar.set_var("IsDeploymentReady", lambda: self.deployment_ready)
            gwvar.set_var("NumEntities", lambda: len(entity_manager.entities()))
            gwvar.set_var("MigrateIn", lambda: {
                "count": self._migrate_in_count,
                "bytes": self._migrate_in_bytes,
                "max_bytes": self._migrate_in_max,
            })

            def _fattest():
                # Largest entity by serialized attr size, broken down by
                # top-level key — names the payload that bloats migrations.
                # One serialize per entity (per-key sizes summed), not two:
                # /vars runs this synchronously on the game loop.
                best = None
                for e in entity_manager.entities().values():
                    keys = {k: len(json.dumps(v, default=str))
                            for k, v in e.attrs.to_dict().items()}
                    sz = sum(keys.values())
                    if best is None or sz > best["bytes"]:
                        best = {"type": e.typename, "bytes": sz,
                                "keys": keys}
                return best

            gwvar.set_var("FattestEntity", _fattest)
            # Per-type counts: the leak-hunting view (a soak that grows
            # NumEntities names its culprit here).
            def _counts():
                out: dict[str, int] = {}
                for e in entity_manager.entities().values():
                    out[e.typename] = out.get(e.typename, 0) + 1
                return out
            gwvar.set_var("EntityCounts", _counts)
            from goworld_tpu.utils import debug_http

            debug_http.set_health_provider(self._health)
            # Pull-sampled telemetry gauge beside the gwvar probe: /metrics
            # scrapers get entity counts without touching /vars.
            telemetry.gauge(
                "game_entities", "Live entities on this game process.",
                ("gameid",),
            ).labels(str(self.gameid)).set_function(
                lambda: len(entity_manager.entities()))
            debug_srv = await setup_http_server(game_cfg.http_addr if game_cfg else "")
            if tcfg is not None and tcfg.history_dir:
                # Black-box history ring (telemetry/history.py): its own
                # cadence task off the logic loop; the finally below
                # writes the final frame — after a kill this ring is the
                # only record of the process's last ticks.
                from goworld_tpu.telemetry import history as history_mod
                import os as _os

                hist_writer = history_mod.HistoryWriter(
                    _os.path.join(tcfg.history_dir, f"game{self.gameid}"),
                    f"game{self.gameid}",
                    interval=tcfg.history_interval,
                    segment_bytes=tcfg.history_segment_bytes,
                    segments=tcfg.history_segments,
                    health=self._health, flight=self.flight)
                history_mod.set_active_writer(hist_writer)
                hist_task = asyncio.get_running_loop().create_task(
                    hist_writer.run())
            lbc_task = asyncio.get_running_loop().create_task(self._lbc_loop())
            gwlog.infof("game %d starting (restore=%s)", self.gameid, self.restore)
            gwlog.infof(consts.GAME_STARTED_TAG)
            await self._main_loop()
        finally:
            if lbc_task is not None:
                lbc_task.cancel()
            if hist_task is not None:
                hist_task.cancel()
            if hist_writer is not None:
                # Final frame: the ring's newest entry carries the last
                # flight-recorder ticks + census this incarnation saw.
                hist_writer.close()
                from goworld_tpu.telemetry import history as history_mod

                history_mod.clear_active_writer(hist_writer)
            if debug_srv is not None:
                await debug_srv.stop()
            # IsDeploymentReady is guaranteed always-published (gwvar.go:27-29
            # sets it at init); flip it back to False rather than unsetting so
            # a co-hosted /vars endpoint keeps serving it after shutdown.
            gwvar.set_var("IsDeploymentReady", False)
            gwvar.unset("NumEntities")
            # These closures capture self + the entity graph: a stopped
            # service must neither serve stale probes nor keep hundreds
            # of MB of entity state alive through the gwvar registry.
            gwvar.unset("MigrateIn")
            gwvar.unset("FattestEntity")
            # Same closure-capture reasoning as the gwvar.unset calls.
            telemetry.gauge("game_entities", labelnames=("gameid",)).remove(
                str(self.gameid))
            from goworld_tpu.utils import debug_http

            debug_http.clear_health_provider(self._health)
            if tracing.flight_recorder() is self.flight:
                tracing.set_flight_recorder(None)
            await self.cluster.stop()
            dispatchercluster.set_cluster(None)
        return self.exit_code or 0

    def _handshake(self, index: int, proxy) -> None:
        # Per-dispatcher entity list: each dispatcher gets ONLY the ids it
        # owns by hash (GetEntityIDsForDispatcher, DispatcherConnMgr.go:79).
        # Sending the full list seeds stale entries on non-owner
        # dispatchers; after a migration (which updates only the owner),
        # the next restore's reconciliation on a non-owner then REJECTS
        # the entity and its game destroys it (seen as vanished avatars +
        # wedged clients in the double-reload soak).
        from goworld_tpu.common import hash_entity_id

        n = len(self.cfg.dispatchers)
        proxy.send_set_game_id(
            self.gameid,
            is_reconnect=self.deployment_ready,
            is_restore=self.restore,
            is_ban_boot_entity=not self.boot_entity,
            entity_ids=[
                eid for eid in entity_manager.entities()
                if hash_entity_id(eid) % n == index
            ],
        )

    def _on_packet(self, index: int, msgtype: int, packet: Packet) -> None:
        self._queue.put_nowait((msgtype, packet))

    def _on_dispatcher_disconnect(self, index: int) -> None:
        # Sends to the lost dispatcher buffer in its replay ring (byte-
        # capped) and flush after the reconnect handshake — see
        # dispatchercluster/cluster.py.
        gwlog.warnf("game %d: dispatcher %d disconnected; buffering sends "
                    "until reconnect", self.gameid, index)

    def _health(self) -> dict:
        """One JSON object for GET /healthz (and the /snapshot row the
        cluster collector aggregates)."""
        # Client-binding census by gate + the generations those bindings
        # carry: the collector's conservation law (clients bound on games
        # == clients connected on gates) and stale-generation check read
        # exactly these (telemetry/collector.py summarize).
        clients = 0
        gate_gens: dict[str, set] = {}
        for e in entity_manager.entities().values():
            c = e.client
            if c is None:
                continue
            clients += 1
            gate_gens.setdefault(str(c.gateid), set()).add(c.gate_gen)
        # A locally-hosted RebalancePlannerService shard surfaces its
        # planning state here: /cluster's REBAL view and the pause/
        # failover alerts read exactly this row (the dispatcher's healthz
        # only carries last_result in driver mode).
        planner = None
        for e in entity_manager.entities().values():
            if (e.typename == "RebalancePlannerService"
                    and not e.is_destroyed()):
                planner = {
                    "last_result": e.planner.last_result,
                    "reporting_games": e.planner.reports.games(),
                }
                break
        return {
            "kind": "game",
            "id": self.gameid,
            "uptime_s": round(time.monotonic() - self._started_at, 3),
            "deployment_ready": self.deployment_ready,
            "run_state": self.run_state,
            "entities": len(entity_manager.entities()),
            "clients": clients,
            "queue_depth": self.queue_depth(),
            "client_gate_gens": {g: sorted(s) for g, s in gate_gens.items()},
            "rebalance_planner": planner,
            "online_games": sorted(self.online_games),
            "dispatcher_links": (
                self.cluster.link_states() if self.cluster is not None
                else []),
        }

    def _install_signal_handlers(self) -> None:
        loop = asyncio.get_running_loop()
        try:
            loop.add_signal_handler(signal.SIGTERM, self.terminate)
            loop.add_signal_handler(signal.SIGHUP, self.start_freeze)
        except (NotImplementedError, RuntimeError):
            pass  # non-main thread (tests) or unsupported platform

    # --- main loop (GameService.go:76-187) -----------------------------------

    async def _main_loop(self) -> None:
        tick = consts.GAME_SERVICE_TICK_INTERVAL
        rt = entity_manager.runtime
        # Per-tick phase attribution (telemetry/phases.py): dispatch =
        # packet handling, entity_logic = timers+crontab+post, aoi =
        # poll/dispatch/deliver of the batched engine, sync_send = the
        # batched position-sync push. begin() runs AFTER the queue wait so
        # idle time never pollutes the dispatch phase; "total" is the
        # busy span of each iteration. Served on /metrics as
        # game_tick_phase_seconds{phase=...}.
        tracer = telemetry.PhaseTracer(
            "game_tick_phase_seconds",
            ("dispatch", "entity_logic", "aoi", "sync_send"),
            help="Busy wall seconds per game-loop tick, by phase "
                 "(dispatch|entity_logic|aoi|sync_send|total).",
        )
        # Events delivered by the last AOI tick (set by the batched
        # engine; stays 0 on xzlist) — sampled into each flight record.
        aoi_backlog = telemetry.gauge("aoi_event_backlog")
        while True:
            try:
                # Wake at the next position-sync deadline when it lands
                # inside the tick window: a fixed 5 ms wait ADDS to the
                # iteration's work time, so the configured sync rate ran
                # ~25% slow on a quiet queue (6.3 ms achieved periods at a
                # 5 ms interval — bench.py --fanout is cadence-bound on
                # exactly this).
                timeout = tick
                if self.position_sync_interval > 0:
                    due = (self._last_sync_collect
                           + self.position_sync_interval - time.monotonic())
                    if due < timeout:
                        timeout = max(0.0, due)
                msgtype, packet = await asyncio.wait_for(
                    self._queue.get(), timeout=timeout)
                tracer.begin()
                self._last_packet_at = time.monotonic()
                self._handle_packet(msgtype, packet)
                # Drain whatever else arrived without waiting.
                while True:
                    try:
                        msgtype, packet = self._queue.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    self._handle_packet(msgtype, packet)
            except asyncio.TimeoutError:
                tracer.begin()
            tracer.mark("dispatch")
            # Ingress seam 2 (beside the gate's client-RPC receive): game-
            # originated work — timers firing RPCs, crontab jobs — head-
            # samples a fresh root so server-side request chains are
            # traceable too. One coin flip per 5 ms tick; sends inside the
            # scope carry the context across the cluster.
            timer_scope = tracing.root_scope("game.timer_tick")
            if timer_scope is None:
                rt.timer_service.tick()
            else:
                timer_scope.args["gameid"] = self.gameid
                if not self._tick_trace_id:
                    self._tick_trace_id = timer_scope.ctx.trace_id
                with timer_scope:
                    rt.timer_service.tick()
            # Per-class batched behaviors: ONE on_tick_batch call per
            # adopted class over its entities' slab view — the vectorized
            # replacement for per-entity timers (entity/slabs.py).
            rt.slabs.run_tick_batches()
            # Rebalance state machine: deadlines, rollbacks, bounce
            # confirmation for in-flight commanded migrations.
            self.migrator.tick(time.monotonic())
            tracer.mark("entity_logic")
            # NOTE on the multi-HOST (DCN) tier: the wait=False machinery
            # below is lockstep-SAFE as is. Frame-skip only DEFERS a
            # dispatch index (tick dispatches 0,1,2,... on every process,
            # never skipping one), and delivery happens only when the
            # in-flight step is observed ready — so a fast game is paced by
            # readiness gating instead of blocking in a collective, a dead
            # peer degrades to the wedge-watchdog warning (RPCs keep
            # flowing) instead of freezing the loop, and per-process
            # adaptive cadences cannot diverge the global op sequence.
            if rt.aoi_service is not None:
                # AOI rides the position-sync cadence (reference §3.3: AOI
                # updates feed client create/destroy alongside position
                # syncs), NOT the 5 ms loop tick — dispatching every loop
                # iteration ran the device at 100% duty cycle and starved
                # single-core hosts. wait=False: never stall the loop on
                # device compute — frame-skip and let RPCs keep flowing.
                now_aoi = time.monotonic()
                # Ungated readiness probe FIRST (every loop iteration): the
                # turnaround sample must be independent of the cadence gate
                # or the gate re-measures itself and doubles unbounded
                # (poll_ready docstring).
                rt.aoi_service.poll_ready()
                # Cadence stretches to 2x the measured step turnaround when
                # compute exceeds the configured interval — caps engine
                # duty at ~50% under overload instead of dispatching
                # back-to-back (graceful degradation; batched.py).
                cadence = max(
                    self.position_sync_interval,
                    2.0 * rt.aoi_service.last_step_duration,
                )
                if now_aoi - self._last_aoi_tick >= cadence:
                    # Advance the cadence timer only on an actual dispatch:
                    # a frame-skip (None) keeps probing every 5 ms loop
                    # iteration so a step finishing just past the boundary
                    # isn't penalized a whole extra interval.
                    if rt.aoi_service.tick(wait=False) is not None:
                        self._last_aoi_tick = now_aoi
                        self._aoi_wedge_warned = False
                # Watchdog: a step that never becomes ready (wedged device)
                # would frame-skip forever with AOI silently dead while RPCs
                # keep flowing (ADVICE r3). Warn once per incident at 10x
                # the cadence (generous: covers jit recompiles on growth).
                age = rt.aoi_service.in_flight_age()
                if age > max(10.0 * cadence, 30.0):
                    if not self._aoi_wedge_warned:
                        self._aoi_wedge_warned = True
                        gwlog.errorf(
                            "game %d: in-flight AOI step not ready after "
                            "%.1f s (cadence %.3f s) — device wedged? AOI "
                            "delivery is stalled; RPCs keep running",
                            self.gameid, age, cadence,
                        )
            tracer.mark("aoi")
            crontab.check()
            post.tick()
            tracer.mark("entity_logic")
            now = time.monotonic()
            if now - self._last_sync_collect >= self.position_sync_interval:
                # Scheduled-rate cadence: advance the deadline by the
                # INTERVAL (not to `now`) so a loop iteration landing late
                # doesn't stretch the average sync period — the configured
                # position_sync_interval is a rate, and under load the old
                # fixed-delay reset ran it ~25% slow (5 ms config, ~6.2 ms
                # achieved — measured by bench.py --fanout, where delivered
                # records are cadence-bound). Clamped to one interval of
                # backlog: a long stall must not trigger a catch-up burst.
                self._last_sync_collect = max(
                    self._last_sync_collect + self.position_sync_interval,
                    now - self.position_sync_interval,
                )
                self._send_entity_sync_infos()
                tracer.mark("sync_send")
            committed = tracer.commit()
            if committed is not None:
                t0, total, phases = committed
                # Flight recorder: one compact record per tick; a tick
                # over the slow budget dumps the ring as ONE WARN and
                # keeps it on GET /flight.
                self.flight.record(
                    t0, total, phases,
                    queue_depth=self._queue.qsize(),
                    entities=len(entity_manager.entities()),
                    aoi_backlog=int(aoi_backlog.value),
                )
                if self._tick_trace_id:
                    # PhaseTracer boundaries as span events: the tick that
                    # handled a sampled packet lays its phase budget on
                    # the same timeline as that packet's spans.
                    tracing.record_phase_spans(
                        self._tick_trace_id, t0, phases)
                    self._tick_trace_id = 0
            if self.run_state == RS_TERMINATING:
                self._do_terminate()
                return
            if self.run_state == RS_FREEZING:
                if self._freeze_acks >= len(self.cfg.dispatchers):
                    # Deterministic fence (ADVICE r4): each dispatcher
                    # emits its ack on the SAME TCP stream strictly after
                    # installing the block, and acks are counted here at
                    # PROCESSING time — so per-connection FIFO (socket →
                    # reader task → logic queue) guarantees that every
                    # packet a dispatcher forwarded pre-block (e.g. a
                    # REAL_MIGRATE carrying an avatar's entire state) has
                    # already been processed by the time the count reaches
                    # N. Packets a dispatcher received post-block go to
                    # its pending buffer and are delivered after restore.
                    # Nothing can still be in flight: freeze NOW — no
                    # probabilistic quiet-window wait (a migrate delayed
                    # past the old 0.3 s window by kernel buffering was
                    # still lost; the fence cannot miss it).
                    self._do_freeze()
                    return
                if (
                    self._freeze_started_at
                    and now - self._freeze_started_at
                    > consts.FREEZE_ACK_TIMEOUT
                ):
                    # Safety net: a dead/hung dispatcher would otherwise
                    # wedge the freeze forever. Fall back to the
                    # quiescence heuristic — freeze after a quiet window,
                    # bounded by the drain cap.
                    if not self._freeze_acked_at:
                        gwlog.errorf(
                            "game %d: only %d/%d freeze acks after %.0f s "
                            "— falling back to quiescent-window freeze",
                            self.gameid, self._freeze_acks,
                            len(self.cfg.dispatchers),
                            consts.FREEZE_ACK_TIMEOUT,
                        )
                        self._freeze_acked_at = now
                    quiet = now - self._last_packet_at
                    if (
                        quiet >= consts.FREEZE_QUIESCENT_WINDOW
                        or now - self._freeze_acked_at > consts.FREEZE_DRAIN_CAP
                    ):
                        self._do_freeze()
                        return

    def _send_entity_sync_infos(self) -> None:
        """Push batched position syncs, one coalesced packet per gate
        (§3.3; rows are selected and packed as pure column ops over the
        entity slabs — entity_manager.collect_entity_sync_infos). Wall
        time lands on fanout_hop_seconds_total{hop="game_collect"|
        "game_pack"}; the dispatcher-link writes below land on game_send —
        the game-side hops of the per-hop breakdown bench.py --fanout
        reports."""
        per_gate = entity_manager.collect_entity_sync_infos()
        if not per_gate:
            return
        t0 = time.perf_counter()
        qb = entity_manager.runtime.slabs.sync.quantize_bits
        for gateid, (full, delta) in per_gate.items():
            conn = dispatchercluster.select_by_gate_id(gateid)
            if full:
                conn.send_sync_position_yaw_on_clients(gateid, full)
            if delta:
                conn.send_sync_position_yaw_delta_on_clients(
                    gateid, qb, delta)
        _HOP_GAME_SEND.inc(time.perf_counter() - t0)

    # --- packet handlers (GameService.go:92-157) ------------------------------

    def _handle_packet(self, msgtype: int, packet: Packet) -> None:
        scope = None
        if packet.trace is not None:
            # Sampled request: the handling span (incl. local queue dwell
            # as a child) parents onto the dispatcher's routing span; any
            # reply RPC sent inside re-attaches the trailer toward the
            # client's gate.
            scope = tracing.continue_from_packet(
                packet, "game.handle", dwell_name="game.queue_dwell")
            scope.args["msgtype"] = int(msgtype)
            scope.args["gameid"] = self.gameid
            if not self._tick_trace_id:
                self._tick_trace_id = packet.trace.trace_id
        try:
            if scope is None:
                self._dispatch_packet(msgtype, packet)
            else:
                with scope:
                    self._dispatch_packet(msgtype, packet)
        except Exception:
            gwlog.trace_error("game %d: error handling msgtype %s", self.gameid, msgtype)

    def _dispatch_packet(self, msgtype: int, packet: Packet) -> None:
        if msgtype == MsgType.CALL_ENTITY_METHOD:
            eid = packet.read_entity_id()
            method = packet.read_varstr()
            args = tuple(packet.read_args())
            entity_manager.handle_call(eid, method, args, None)
        elif msgtype == MsgType.CALL_ENTITY_METHOD_FROM_CLIENT:
            eid = packet.read_entity_id()
            method = packet.read_varstr()
            args = tuple(packet.read_args())
            clientid = packet.read_client_id()
            entity_manager.handle_call(eid, method, args, clientid)
        elif msgtype == MsgType.SYNC_POSITION_YAW_FROM_CLIENT:
            for eid, x, y, z, yaw in unpack_sync_records(packet.payload):
                e = entity_manager.get_entity(eid)
                if e is not None:
                    e.on_sync_position_yaw_from_client(x, y, z, yaw)
        elif msgtype == MsgType.NOTIFY_CLIENT_CONNECTED:
            clientid = packet.read_client_id()
            gateid = packet.read_uint16()
            boot_eid = packet.read_entity_id()
            gate_gen = (packet.read_uint32()
                        if packet.unread_len() >= 4 else 0)
            self._handle_client_connected(clientid, gateid, boot_eid,
                                          gate_gen)
        elif msgtype == MsgType.NOTIFY_CLIENT_DISCONNECTED:
            clientid = packet.read_client_id()
            packet.read_entity_id()
            owner = entity_manager.get_client_owner(clientid)
            if owner is not None:
                owner.notify_client_disconnected()
        elif msgtype == MsgType.CREATE_ENTITY_SOMEWHERE:
            packet.read_uint16()
            typename = packet.read_varstr()
            eid = packet.read_entity_id()
            attrs = packet.read_data()
            self._handle_create_entity_somewhere(typename, eid, attrs)
        elif msgtype == MsgType.LOAD_ENTITY_SOMEWHERE:
            packet.read_uint16()
            typename = packet.read_varstr()
            eid = packet.read_entity_id()
            entity_manager.load_entity_locally(typename, eid)
        elif msgtype == MsgType.QUERY_SPACE_GAMEID_FOR_MIGRATE_ACK:
            spaceid = packet.read_entity_id()
            eid = packet.read_entity_id()
            gameid = packet.read_uint16()
            nonce = packet.read_uint32()
            e = entity_manager.get_entity(eid)
            if e is not None:
                e.on_query_space_gameid_ack(spaceid, gameid, nonce)
        elif msgtype == MsgType.MIGRATE_REQUEST_ACK:
            eid = packet.read_entity_id()
            spaceid = packet.read_entity_id()
            space_gameid = packet.read_uint16()
            nonce = packet.read_uint32()
            e = entity_manager.get_entity(eid)
            if e is not None:
                e.on_migrate_request_ack(spaceid, space_gameid, nonce)
        elif msgtype == MsgType.REAL_MIGRATE:
            eid = packet.read_entity_id()
            packet.read_uint16()
            raw_len = packet.unread_len()
            data = packet.read_data()
            if not isinstance(data, dict):
                raise ValueError(
                    f"REAL_MIGRATE body for {eid} is "
                    f"{type(data).__name__}, expected dict")
            self._migrate_in_count += 1
            self._migrate_in_bytes += raw_len
            if raw_len > self._migrate_in_max:
                self._migrate_in_max = raw_len
            entity_manager.restore_entity(eid, data, is_migrate=True)
            # Normal arrival → start the newcomer's re-move cooldown;
            # BOUNCE of our own pending departure (dispatcher returned it
            # because the target game died) → roll the migration back.
            self.migrator.on_arrived(eid, time.monotonic())
        elif msgtype == MsgType.REBALANCE_MIGRATE:
            from_space = packet.read_entity_id()
            to_space = packet.read_entity_id()
            to_game = packet.read_uint16()
            count = packet.read_uint16()
            self._handle_rebalance_migrate(from_space, to_space, to_game, count)
        elif msgtype == MsgType.REBALANCE_MIGRATE_SPACE:
            spaceid = packet.read_entity_id()
            to_game = packet.read_uint16()
            self._handle_rebalance_migrate_space(spaceid, to_game)
        elif msgtype == MsgType.SPACE_MIGRATE_PREPARE_ACK:
            spaceid = packet.read_entity_id()
            dispatcherid = packet.read_uint16()
            self.migrator.on_space_prepare_ack(
                spaceid, dispatcherid, time.monotonic())
        elif msgtype == MsgType.SPACE_MIGRATE_DATA:
            spaceid = packet.read_entity_id()
            packet.read_uint16()
            raw_len = packet.unread_len()
            bundle = packet.read_data()
            if not isinstance(bundle, dict):
                raise ValueError(
                    f"SPACE_MIGRATE_DATA body for {spaceid} is "
                    f"{type(bundle).__name__}, expected dict")
            # Trailing source_game (same convention as REAL_MIGRATE's):
            # present so a dispatcher sweep can bounce the payload home.
            source_game = (packet.read_uint16()
                           if packet.unread_len() >= 2 else 0)
            self._migrate_in_count += 1
            self._migrate_in_bytes += raw_len
            if raw_len > self._migrate_in_max:
                self._migrate_in_max = raw_len
            self.migrator.on_space_data(
                spaceid, bundle, source_game, time.monotonic())
        elif msgtype == MsgType.SPACE_MIGRATE_ABORT:
            spaceid = packet.read_entity_id()
            reason = packet.read_varstr()
            self.migrator.on_space_abort(spaceid, reason, time.monotonic())
        elif msgtype == MsgType.CALL_NIL_SPACES:
            packet.read_uint16()
            method = packet.read_varstr()
            args = tuple(packet.read_args())
            ns = entity_manager.get_nil_space()
            if ns is not None:
                ns.on_call_from_remote(method, args, None)
        elif msgtype == MsgType.SET_GAME_ID_ACK:
            ack = packet.read_data()
            if not isinstance(ack, dict):
                raise ValueError(
                    f"SET_GAME_ID_ACK body is {type(ack).__name__}, "
                    f"expected dict")
            self._handle_set_game_id_ack(ack)
        elif msgtype == MsgType.NOTIFY_GAME_CONNECTED:
            self.online_games.add(packet.read_uint16())
        elif msgtype == MsgType.NOTIFY_GAME_DISCONNECTED:
            self.online_games.discard(packet.read_uint16())
        elif msgtype == MsgType.NOTIFY_GATE_DISCONNECTED:
            gateid = packet.read_uint16()
            valid_gen = (packet.read_uint32()
                         if packet.unread_len() >= 4 else 0)
            entity_manager.on_gate_disconnected(gateid, valid_gen)
        elif msgtype == MsgType.NOTIFY_DEPLOYMENT_READY:
            self._on_deployment_ready()
        elif msgtype == MsgType.KVREG_REGISTER:
            key = packet.read_varstr()
            value = packet.read_varstr()
            kvreg.on_registered(key, value)
        elif msgtype == MsgType.START_FREEZE_GAME_ACK:
            self._freeze_acks += 1
        else:
            gwlog.warnf("game %d: unhandled msgtype %s", self.gameid, msgtype)

    def _handle_rebalance_migrate(self, from_space: str, to_space: str,
                                  to_game: int, count: int) -> None:
        """Dispatcher rebalance command: move up to ``count`` eligible
        entities of ``from_space`` into ``to_space`` (a same-kind space on
        ``to_game``) through the hardened migrate path. A stale command —
        the space moved, emptied, or died since the planner's report —
        degrades to moving fewer (or zero) entities, never to guessing."""
        space = entity_manager.get_space(from_space)
        if space is None or space.is_destroyed():
            gwlog.warnf("game %d: rebalance command for unknown space %s",
                        self.gameid, from_space)
            return
        moved = self.migrator.handle_command(
            space, to_space, count, time.monotonic())
        gwlog.infof(
            "game %d: rebalance command — migrating %d/%d entities of "
            "space %s to %s on game %d", self.gameid, moved, count,
            from_space, to_space, to_game)

    def _handle_rebalance_migrate_space(self, spaceid: str,
                                        to_game: int) -> None:
        """Dispatcher rebalance command: hand the WHOLE space to
        ``to_game`` through the two-phase SPACE_MIGRATE protocol. Same
        staleness contract as the entity command: an unknown / already
        in-flight / cooling-down space degrades to doing nothing."""
        space = entity_manager.get_space(spaceid)
        if space is None or space.is_destroyed():
            gwlog.warnf(
                "game %d: space-rebalance command for unknown space %s",
                self.gameid, spaceid)
            return
        started = self.migrator.handle_space_command(
            space, to_game, time.monotonic())
        gwlog.infof(
            "game %d: space-rebalance command — handoff of %s (%d members)"
            " to game %d %s", self.gameid, spaceid,
            space.get_entity_count(), to_game,
            "started" if started else "refused")

    def _handle_client_connected(self, clientid: str, gateid: int,
                                 boot_eid: str, gate_gen: int = 0) -> None:
        """Create the boot entity and bind the fresh client
        (GameService.go:413-422)."""
        if not self.boot_entity:
            gwlog.errorf("game %d: client connected but no boot entity configured", self.gameid)
            return
        e = entity_manager.create_entity_locally(self.boot_entity, eid=boot_eid)
        e.set_client(GameClient(clientid, gateid, e.id, gate_gen=gate_gen))

    def _handle_create_entity_somewhere(self, typename: str, eid: str, attrs: dict) -> None:
        kind = attrs.pop("_kind", None)
        desc = entity_manager.get_entity_type_desc(typename)
        if desc.is_space and kind is not None:
            entity_manager.create_space_locally(int(kind), eid=eid, attrs=attrs or None)
        else:
            entity_manager.create_entity_locally(typename, eid=eid, attrs=attrs or None)

    def _handle_set_game_id_ack(self, ack: dict) -> None:
        """Reconnect reconciliation + kvreg replay (GameService.go:341-377)."""
        self.online_games = set(ack.get("online_games", []))
        for eid in ack.get("rejected", []):
            e = entity_manager.get_entity(eid)
            if e is not None:
                gwlog.warnf("game %d: destroying rejected entity %s", self.gameid, e)
                e.destroy()
        kvreg.replay(ack.get("kvreg", {}))
        if ack.get("ready"):
            self._on_deployment_ready()

    def _on_deployment_ready(self) -> None:
        if self.deployment_ready:
            return
        self.deployment_ready = True
        gwlog.infof("game %d: deployment ready", self.gameid)
        entity_manager.on_game_ready()
        from goworld_tpu import service as service_mod

        service_mod.on_deployment_ready()

    # --- terminate (GameService.go:194-213) -----------------------------------

    def terminate(self) -> None:
        if self.run_state == RS_RUNNING:
            self.run_state = RS_TERMINATING

    def _do_terminate(self) -> None:
        gwlog.infof("game %d terminating: saving and destroying all entities", self.gameid)
        entity_manager.save_entities_batch()
        for e in list(entity_manager.entities().values()):
            if not e.is_space_entity():
                gwutils.run_panicless(e.destroy)
        for s in list(entity_manager.entities().values()):
            gwutils.run_panicless(s.destroy)
        storage.drain_for_shutdown()
        post.tick()
        self.run_state = RS_TERMINATED
        self.exit_code = 0

    # --- freeze (GameService.go:217-310, game.go:163-188) ---------------------

    def start_freeze(self) -> None:
        """SIGHUP entry: ask every dispatcher to buffer our packets."""
        if self.run_state != RS_RUNNING:
            return
        gwlog.infof("game %d freezing: notifying %d dispatchers", self.gameid, len(self.cfg.dispatchers))
        self._freeze_acks = 0
        self._freeze_started_at = time.monotonic()
        self.run_state = RS_FREEZING
        for sender in dispatchercluster.select_all():
            sender.send_start_freeze_game()

    def _do_freeze(self) -> None:
        # AOI flush first: its delivered callbacks may post work or queue
        # storage saves, which the barriers below must then drain.
        aoi = entity_manager.runtime.aoi_service
        if aoi is not None:
            aoi.flush()  # no in-flight AOI diffs may survive the freeze
        post.tick()
        async_jobs.wait_clear()
        data = entity_manager.freeze_entities(self.gameid)
        path = freeze_filename(self.gameid)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f)
        os.replace(tmp, path)
        gwlog.infof("game %d freezed to %s (%d spaces, %d entities)",
                    self.gameid, path, len(data["spaces"]), len(data["entities"]))
        gwlog.infof(consts.FREEZED_TAG)
        self.run_state = RS_FREEZED
        self.exit_code = 2  # CLI restarts with -restore

    def _restore_freezed_entities(self) -> None:
        """restore.go:12-34: read the freeze file and rebuild in 3 passes."""
        path = freeze_filename(self.gameid)
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        entity_manager.restore_freezed_entities(data)
        os.remove(path)
        gwlog.infof("game %d restored %d spaces + %d entities from %s",
                    self.gameid, len(data["spaces"]), len(data["entities"]), path)

    # --- load reporting (lbc/gamelbc.go:17-39, extended per ROADMAP 1) --------

    def queue_depth(self) -> int:
        return self._queue.qsize()

    async def _lbc_loop(self) -> None:
        """Every [rebalance] report_interval: send the RICH load report
        (cpu%, entities, tick p95, queue depth, per-space populations —
        rebalance/report.py) to every dispatcher. Supersedes the
        reference's cpu-only GAME_LBC_INFO: the dispatcher feeds the same
        cpu number into its LBC choose-game heap AND the rebalancer's
        planner from this one packet."""
        from goworld_tpu.rebalance import build_load_report

        rbcfg = getattr(self.cfg, "rebalance", None)
        to_service = (rbcfg is not None and rbcfg.enabled
                      and rbcfg.planner_service)
        last_cpu = time.process_time()
        last_wall = time.monotonic()
        while True:
            await asyncio.sleep(self._report_interval)
            cpu, wall = time.process_time(), time.monotonic()
            pct = 100.0 * (cpu - last_cpu) / max(1e-9, wall - last_wall)
            last_cpu, last_wall = cpu, wall
            self.last_cpu_pct = pct
            report = build_load_report(self)
            for sender in dispatchercluster.select_all():
                sender.send_game_load_report(report)
            if to_service:
                # Planner-service mode ALSO pushes the report to the
                # sharded planner (deferred-call path: a report racing the
                # failover window delivers to the NEW shard). Dispatchers
                # keep receiving theirs — the LBC heap and /cluster load
                # scores live there regardless of who plans.
                from goworld_tpu import service as service_mod
                from goworld_tpu.rebalance import planner_service as ps

                service_mod.call_service_shard_key(
                    ps.SERVICE_NAME, ps.REPORT_SHARD_KEY, "ReportLoad",
                    self.gameid, report)


def run(gameid: int | None = None, restore: bool | None = None) -> int:
    """Process entry point: parse args (game.go:52-61), run the service."""
    import argparse

    from goworld_tpu.config import get as get_config, set_config_file

    parser = argparse.ArgumentParser(description="goworld_tpu game process")
    parser.add_argument("-gid", type=int, default=gameid or 1)
    parser.add_argument("-configfile", type=str, default="")
    parser.add_argument("-log", type=str, default="")
    parser.add_argument("-restore", action="store_true", default=bool(restore))
    parser.add_argument("-d", action="store_true",
                        help="daemonize (binutil.Daemonize, game.go:70-77)")
    args, _ = parser.parse_known_args()
    if args.configfile:
        set_config_file(args.configfile)
    cfg = get_config()
    game_cfg = cfg.games.get(args.gid)
    if args.d:
        from goworld_tpu.utils.binutil import daemonize

        daemonize((game_cfg.log_file if game_cfg else None)
                  or f"game{args.gid}.daemon.log")
    gwlog.setup(
        level=(args.log or (game_cfg.log_level if game_cfg else "info")),
        logfile=(game_cfg.log_file if game_cfg else None) or None,
        fmt=cfg.log.format,
    )
    gwlog.set_source(f"game{args.gid}")
    svc = GameService(args.gid, cfg, restore=args.restore)
    return asyncio.run(svc.run_async())

"""Game process: the single-threaded entity logic loop.

Reference parity: ``components/game`` (SURVEY.md §2.2, §3.1) — user code
supplies a main that calls ``goworld.run()``; the GameService main loop
selects over the packet queue and a 5 ms ticker, fires timers, drains the
post queue, and periodically collects position-sync infos. SIGTERM is a
graceful terminate (save + destroy all entities); SIGHUP freezes the process
to ``game<N>_freezed.dat`` for hot reload (game.go:138-194).
"""

from goworld_tpu.game.service import GameService, run

__all__ = ["GameService", "run"]

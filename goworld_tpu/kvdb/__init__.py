"""Global key-value store for game logic (e.g. username → avatarID).

Reference parity: ``engine/kvdb/kvdb.go:40-207`` — Get/Put/GetOrPut/GetRange
run on a serial async job group so operations stay ordered; callbacks are
posted back to the main loop; the backend auto-reopens on connection error
(here: backends are local, so reopen reduces to retry-on-error once).

Backend SPI mirrors ``kvdb_types.go:4-25``. Backends: filesystem (JSON file
per key) and sqlite (ordered keys → efficient GetRange).
"""

from __future__ import annotations

from typing import Callable, Optional

from goworld_tpu.utils import async_jobs

_GROUP = "kvdb"
_backend = None


def initialize(kvdb_config) -> None:
    global _backend
    _backend = make_backend(kvdb_config.type, kvdb_config)


def make_backend(kind: str, cfg):
    if kind == "filesystem":
        from goworld_tpu.kvdb.filesystem import FilesystemKVDB

        return FilesystemKVDB(cfg.directory)
    if kind == "sqlite":
        from goworld_tpu.kvdb.sqlite import SQLiteKVDB

        return SQLiteKVDB(cfg.directory)
    if kind == "redis":
        from goworld_tpu.kvdb.redis import RedisKVDB

        return RedisKVDB(cfg.url)
    if kind == "redis_cluster":
        from goworld_tpu.kvdb.redis_cluster import RedisClusterKVDB

        return RedisClusterKVDB(list(cfg.start_nodes))
    if kind == "mongodb":
        from goworld_tpu.kvdb.mongodb import MongoKVDB

        return MongoKVDB(
            cfg.url, db=getattr(cfg, "db", "goworld"),
            collection=getattr(cfg, "collection", "kvdb"),
        )
    if kind == "mysql":
        from goworld_tpu.kvdb.mysql import MySQLKVDB

        return MySQLKVDB(cfg.url)
    raise ValueError(
        f"unknown kvdb type {kind!r} "
        f"(available: filesystem, sqlite, redis, redis_cluster, mongodb, mysql)"
    )


def set_backend(backend) -> None:
    global _backend
    _backend = backend


def initialized() -> bool:
    return _backend is not None


def _submit(routine, callback):
    cb = None if callback is None else (lambda result, err: callback(result, err))
    async_jobs.append_job(_GROUP, routine, cb)


def get(key: str, callback: Callable) -> None:
    """callback(value | None, err) — missing keys yield None (kvdb.go:86-105)."""
    _submit(lambda: _backend.get(key), callback)


def put(key: str, val: str, callback: Optional[Callable] = None) -> None:
    _submit(lambda: _backend.put(key, val), callback)


def get_or_put(key: str, val: str, callback: Callable) -> None:
    """Atomically: return existing value, else set ``val`` and return None
    (kvdb.go:139-152 — the login/claim primitive)."""
    _submit(lambda: _backend.get_or_put(key, val), callback)


def get_range(begin: str, end: str, callback: Callable) -> None:
    """callback(list[(key, value)]) for begin <= key < end (kvdb.go:154-201)."""
    _submit(lambda: _backend.get_range(begin, end), callback)


def wait_clear(timeout: float = 30.0) -> bool:
    return async_jobs.wait_clear(timeout)

"""Redis Cluster KVDB backend.

Reference parity:
``engine/kvdb/backend/kvdbrediscluster/kvdb_redis_cluster.go:1`` — same
``_KV_`` namespace and contract as the single-node backend, routed through
the cluster client: get_or_put stays an atomic SETNX on the key's owning
master; get_range scans every master and MGETs per slot group.
"""

from __future__ import annotations

from typing import Optional

from goworld_tpu.kvdb.redis import RedisKVDB
from goworld_tpu.netutil.resp_cluster import RespClusterClient


class RedisClusterKVDB(RedisKVDB):
    """All method bodies inherited — only the client construction differs
    (both clients expose the same get/set/setnx/mget/scan_keys surface)."""

    def __init__(
        self, start_nodes: list[str], password: Optional[str] = None
    ) -> None:
        self._client = RespClusterClient(start_nodes, password=password)

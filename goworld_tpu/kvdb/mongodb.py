"""MongoDB KVDB backend over the in-repo OP_MSG client.

Reference parity: ``engine/kvdb/backend/kvdb_mongodb.go`` — one ``kvdb``
collection of {_id: key, v: val}; GetRange is an ordered ``_id`` range
query; get_or_put is an insert racing the unique ``_id`` index (duplicate
key = somebody else holds it — the login-claim primitive).
"""

from __future__ import annotations

from typing import Optional

from goworld_tpu.netutil.mongo import (
    DUPLICATE_KEY,
    MongoClient,
    MongoError,
    parse_mongo_url,
)


class MongoKVDB:
    def __init__(self, url: str, db: str = "goworld",
                 collection: str = "kvdb") -> None:
        self._client = MongoClient(**parse_mongo_url(url))
        self._db = db
        self._coll = collection

    def get(self, key: str) -> Optional[str]:
        doc = self._client.find_one(self._db, self._coll, {"_id": key})
        return None if doc is None else doc.get("v")

    def put(self, key: str, val: str) -> None:
        self._client.upsert(
            self._db, self._coll, {"_id": key}, {"_id": key, "v": val}
        )

    def get_or_put(self, key: str, val: str) -> Optional[str]:
        try:
            self._client.insert(self._db, self._coll, [{"_id": key, "v": val}])
            return None
        except MongoError as err:
            if err.code != DUPLICATE_KEY:
                raise
            return self.get(key)

    def get_range(self, begin: str, end: str) -> list[tuple[str, str]]:
        docs = self._client.find(
            self._db, self._coll,
            {"_id": {"$gte": begin, "$lt": end}},
            sort={"_id": 1},
        )
        return [(d["_id"], d.get("v", "")) for d in docs]

    def close(self) -> None:
        self._client.close()

"""Filesystem KVDB backend: one JSON file holding the whole map.

The kvdb analog of the reference's filesystem entity storage — a zero-dep
local backend for tests and single-host runs. The map is small (login names,
service registrations); every put rewrites the file atomically.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional


class FilesystemKVDB:
    def __init__(self, directory: str, filename: str = "kvdb.json") -> None:
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, filename)
        self._lock = threading.Lock()
        self._data: dict[str, str] = {}
        if os.path.exists(self.path):
            with open(self.path, encoding="utf-8") as f:
                self._data = json.load(f)

    def _flush(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self._data, f)
        os.replace(tmp, self.path)

    def get(self, key: str) -> Optional[str]:
        with self._lock:
            return self._data.get(key)

    def put(self, key: str, val: str) -> None:
        with self._lock:
            self._data[key] = val
            self._flush()

    def get_or_put(self, key: str, val: str) -> Optional[str]:
        with self._lock:
            existing = self._data.get(key)
            if existing is not None:
                return existing
            self._data[key] = val
            self._flush()
            return None

    def get_range(self, begin: str, end: str) -> list[tuple[str, str]]:
        with self._lock:
            return sorted(
                (k, v) for k, v in self._data.items() if begin <= k < end
            )

    def close(self) -> None:
        pass

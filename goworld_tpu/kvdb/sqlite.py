"""SQLite KVDB backend: ordered keys make GetRange a btree scan.

Fills the reference's ``kvdb_mysql``/``kvdb_mongodb`` slot (kvdb_types.go:4-25)
with a serverless local store.
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Optional


class SQLiteKVDB:
    def __init__(self, directory: str, filename: str = "kvdb.sqlite") -> None:
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, filename)
        self._conn = sqlite3.connect(
            self.path, check_same_thread=False, timeout=10.0
        )
        self._lock = threading.Lock()
        with self._lock:
            # WAL lets the other game processes read while one writes;
            # busy_timeout rides out cross-process write contention (every
            # game in a deployment shares this file, like the reference's
            # shared kvdb service).
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA busy_timeout=10000")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (k TEXT PRIMARY KEY, v TEXT NOT NULL)"
            )
            self._conn.commit()

    def get(self, key: str) -> Optional[str]:
        with self._lock:
            row = self._conn.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return row[0] if row else None

    def put(self, key: str, val: str) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO kv (k, v) VALUES (?, ?)"
                " ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                (key, val),
            )
            self._conn.commit()

    def get_or_put(self, key: str, val: str) -> Optional[str]:
        with self._lock:
            row = self._conn.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
            if row is not None:
                return row[0]
            self._conn.execute("INSERT INTO kv (k, v) VALUES (?, ?)", (key, val))
            self._conn.commit()
            return None

    def get_range(self, begin: str, end: str) -> list[tuple[str, str]]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT k, v FROM kv WHERE k >= ? AND k < ? ORDER BY k",
                (begin, end),
            ).fetchall()
        return [(k, v) for k, v in rows]

    def close(self) -> None:
        with self._lock:
            self._conn.close()

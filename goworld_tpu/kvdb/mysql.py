"""MySQL KVDB backend over the in-repo wire-protocol client.

Reference parity: ``engine/kvdb/backend/kvdb_mysql.go`` — ordered VARCHAR
keys make GetRange a btree range scan; get_or_put is INSERT IGNORE racing
the primary key (affected-rows 1 = claimed).
"""

from __future__ import annotations

from typing import Optional

from goworld_tpu.netutil.mysql import MySQLClient, escape, parse_mysql_url

_TABLE = "gw_kv"


class MySQLKVDB:
    def __init__(self, url: str) -> None:
        self._client = MySQLClient(**parse_mysql_url(url))
        self._client.execute(
            f"CREATE TABLE IF NOT EXISTS {_TABLE} ("
            " k VARCHAR(255) NOT NULL PRIMARY KEY,"
            " v TEXT NOT NULL)"
        )

    def get(self, key: str) -> Optional[str]:
        rows = self._client.query(
            f"SELECT v FROM {_TABLE} WHERE k='{escape(key)}'"
        )
        return rows[0][0] if rows else None

    def put(self, key: str, val: str) -> None:
        self._client.execute(
            f"REPLACE INTO {_TABLE} VALUES ('{escape(key)}', '{escape(val)}')"
        )

    def get_or_put(self, key: str, val: str) -> Optional[str]:
        claimed = self._client.execute(
            f"INSERT IGNORE INTO {_TABLE} VALUES "
            f"('{escape(key)}', '{escape(val)}')"
        )
        if claimed:
            return None
        return self.get(key)

    def get_range(self, begin: str, end: str) -> list[tuple[str, str]]:
        rows = self._client.query(
            f"SELECT k, v FROM {_TABLE} WHERE k>='{escape(begin)}'"
            f" AND k<'{escape(end)}' ORDER BY k"
        )
        return [(r[0], r[1]) for r in rows]

    def close(self) -> None:
        self._client.close()

"""Redis KVDB backend over the in-repo RESP2 client.

Reference parity: ``engine/kvdb/backend/kvdb_redis.go:11-69`` — keys carry
a ``_KV_`` namespace prefix; get_or_put is the atomic login-claim
primitive (SETNX); GetRange is a SCAN + sort + MGET, since redis has no
ordered key space (the reference's redis backend shares this shape).
"""

from __future__ import annotations

from typing import Optional

from goworld_tpu.netutil.resp import RespClient, parse_redis_url

_PREFIX = "_KV_"


class RedisKVDB:
    def __init__(self, url: str) -> None:
        self._client = RespClient(**parse_redis_url(url))

    def get(self, key: str) -> Optional[str]:
        return self._client.get(_PREFIX + key)

    def put(self, key: str, val: str) -> None:
        self._client.set(_PREFIX + key, val)

    def get_or_put(self, key: str, val: str) -> Optional[str]:
        # SETNX first: the claim must be atomic under concurrent logins.
        if self._client.setnx(_PREFIX + key, val):
            return None
        return self._client.get(_PREFIX + key)

    def get_range(self, begin: str, end: str) -> list[tuple[str, str]]:
        keys = [
            k[len(_PREFIX):]
            for k in self._client.scan_keys(_PREFIX + "*")
        ]
        keys = sorted(k for k in keys if begin <= k < end)
        vals = self._client.mget([_PREFIX + k for k in keys])
        return [(k, v) for k, v in zip(keys, vals) if v is not None]

    def close(self) -> None:
        self._client.close()

"""Centralized compile-time tunables.

Mirrors the role of the reference's ``engine/consts/consts.go:6-137``: every
magic number that shapes runtime behavior lives here so operators can audit
them in one place.
"""

# --- ticking ----------------------------------------------------------------
# Reference runs 5 ms ticks on game/gate/dispatcher (consts.go:36,46,57).
GAME_SERVICE_TICK_INTERVAL = 0.005  # seconds
GATE_SERVICE_TICK_INTERVAL = 0.005
DISPATCHER_SERVICE_TICK_INTERVAL = 0.005

# --- networking -------------------------------------------------------------
MAX_PACKET_SIZE = 25 * 1024 * 1024  # reference PacketConnection.go:23
SIZE_FIELD_SIZE = 4  # 4-byte little-endian length prefix
PAYLOAD_LEN_MASK = 0x7FFFFFFF  # high bit reserved (reference: compressed flag)
CONNECTION_WRITE_BUFFER_SIZE = 1024 * 1024  # consts.go:14-61
CONNECTION_READ_BUFFER_SIZE = 1024 * 1024
BUFFERED_IO_SIZE = 16 * 1024
FLUSH_INTERVAL = 0.005  # auto-flush cadence (GoWorldConnection.go:437-452)

# --- dispatcher queue bounds (consts.go:30-34) ------------------------------
ENTITY_PENDING_PACKET_QUEUE_MAX_LEN = 1000
GAME_PENDING_PACKET_QUEUE_MAX_LEN = 1_000_000
DISPATCHER_MESSAGE_QUEUE_LEN = 10_000

# --- timeouts ---------------------------------------------------------------
DISPATCHER_MIGRATE_TIMEOUT = 60.0  # consts.go (1 min migrate window)
DISPATCHER_LOAD_TIMEOUT = 60.0
# Freeze buffering window (reference: 10 s, consts.go FREEZE_GAME_TIMEOUT).
# A restarting game here is a fresh Python interpreter (~2-4 s import cost
# per game, restarted sequentially by the CLI); 10 s leaves no headroom on a
# loaded box and an expired block DROPS packets instead of buffering them.
DISPATCHER_FREEZE_GAME_TIMEOUT = 30.0
# Freeze fence: each dispatcher's ack is emitted on the same TCP stream
# strictly after it installs the block, so processing the N-th ack IS the
# proof that every pre-block packet has been processed (game/service.py
# main loop). The quiescence knobs below are only the SAFETY NET for the
# all-acks-never-arrive case (dead dispatcher), entered after
# FREEZE_ACK_TIMEOUT.
FREEZE_ACK_TIMEOUT = 10.0
FREEZE_QUIESCENT_WINDOW = 0.3
FREEZE_DRAIN_CAP = 5.0
RECONNECT_INTERVAL = 1.0  # DispatcherConnMgr reconnect backoff (base)
# Reconnect backoff ceiling: delays grow base * 2^attempt with full jitter
# up to this cap, so a dead dispatcher isn't hammered at 1 Hz by every
# process in the deployment AND a thundering-herd reconnect (all games +
# gates at once after a dispatcher restart) is spread out.
RECONNECT_INTERVAL_MAX = 15.0
CLIENT_HEARTBEAT_TIMEOUT = 30.0  # gate kills silent clients

# --- cluster-link resilience ([cluster] ini section overrides) --------------
# Byte cap of the per-link replay ring: sends to a down dispatcher buffer
# here (drop-OLDEST on overflow, counted on cluster_dropped_packets_total)
# and replay right after the reconnect handshake. 0 restores the legacy
# drop-on-down behavior.
CLUSTER_DOWN_BUFFER_BYTES = 2 * 1024 * 1024
# Liveness deadline for game/gate↔dispatcher links: both ends send a
# HEARTBEAT msgtype on idle links (every timeout/3) and close a link silent
# past the timeout, converting a half-open TCP connection into the normal
# reconnect path instead of an indefinite stall. 0 disables.
CLUSTER_PEER_HEARTBEAT_TIMEOUT = 10.0
# Default wait_connected() deadline (DispatcherClusterBase).
CLUSTER_WAIT_CONNECTED_TIMEOUT = 10.0
# Dispatcher-side reconnect grace: with replay-buffered links a blip is
# steady-state, so an UNPLANNED game/gate disconnect buffers that peer's
# packets for this window (like the freeze window) instead of instantly
# wiping routes / broadcasting peer-death — the reconnect handshake flushes
# the buffer; only a window that lapses becomes a real death. The same
# window buffers packets for not-yet-routed entities (a gate's ring replay
# racing the game's re-handshake into a restarted dispatcher).
DISPATCHER_RECONNECT_BUFFER_WINDOW = 5.0
# Size trigger for position-sync aggregation buffers (dispatcher per-game
# and gate per-dispatcher): a buffer reaching this many bytes flushes
# immediately instead of waiting out the tick/sync interval, so a burst
# pays latency proportional to its size, not the flush cadence.
# 0 disables the trigger ([cluster] sync_flush_bytes overrides).
DISPATCHER_SYNC_FLUSH_BYTES = 32 * 1024

# --- telemetry / tracing ([telemetry] ini section overrides) -----------------
# Head-sampling denominator for distributed traces: 1-in-N ingress events
# (gate client RPC, game timer tick) mint a TraceContext that rides cluster
# packets as a 17-byte trailer. 0 disables tracing entirely; unsampled
# traffic is wire-identical either way (telemetry/tracing.py).
TRACE_SAMPLE_RATE = 1024
# Finished-span ring per process (drop-oldest, trace_spans_dropped_total).
TRACE_RING_SIZE = 4096
# Slow-tick flight recorder: a game tick busier than this many seconds
# dumps the last FLIGHT_RING_SIZE tick records + the tick's sampled spans
# as ONE structured WARN (kept on GET /flight). Default 0.1 s ≈ 2x the
# ~48 ms busy tick of the committed pinned-floor config (BENCH_FLOOR.json:
# 2048 entities / 42k upd/s) — production 5 ms ticks only ever get near it
# when something is genuinely wrong (jit recompile, storage stall, GC).
SLOW_TICK_BUDGET = 0.1
FLIGHT_RING_SIZE = 240

# --- persistence ------------------------------------------------------------
DEFAULT_SAVE_INTERVAL = 300.0  # 5 min (read_config.go:28)
# Save-retry backoff: the reference retries forever at a fixed 1 s
# (storage.go:197-240); here the delay doubles per consecutive failure up
# to the cap, and after STORAGE_CIRCUIT_FAILURE_THRESHOLD consecutive
# failures the per-backend circuit OPENS: further saves defer into a
# byte-capped queue (keeping the single storage worker live for the other
# entities) until a half-open probe after STORAGE_CIRCUIT_COOLDOWN
# succeeds. All overridable via the [storage] ini section.
STORAGE_RETRY_BASE_INTERVAL = 1.0
STORAGE_RETRY_MAX_INTERVAL = 30.0
STORAGE_CIRCUIT_FAILURE_THRESHOLD = 5
STORAGE_CIRCUIT_COOLDOWN = 5.0
STORAGE_DEFERRED_BYTES_CAP = 8 * 1024 * 1024

# --- AOI / TPU compute plane ------------------------------------------------
# Default fixed neighbor-set capacity per entity on the TPU path. The
# reference's go-aoi has no cap; interest sets in practice are bounded by
# design caps (e.g. 100 avatars/space, unity_demo/SpaceService.go:13-15).
AOI_MAX_NEIGHBORS = 128
# Default per-cell capacity of the spatial hash grid (padded, static shape).
AOI_CELL_CAPACITY = 64
# Default position-sync cadence (read_config.go:328,380 → 100 ms).
POSITION_SYNC_INTERVAL = 0.1

# --- debug switches ---------------------------------------------------------
DEBUG_PACKETS = False
DEBUG_SPACES = False
DEBUG_SAVE_LOAD = False
DEBUG_CLIENTS = False
DEBUG_MIGRATE = False

# --- supervisor start tags (binutil consts.go:133-137) ----------------------
# Printed once a process is serving; the CLI start command scans child logs
# for these to sequence dispatchers -> games -> gates.
DISPATCHER_STARTED_TAG = "SUPERVISOR: dispatcher started ok"
GAME_STARTED_TAG = "SUPERVISOR: game started ok"
GATE_STARTED_TAG = "SUPERVISOR: gate started ok"
FREEZED_TAG = "SUPERVISOR: game freezed"

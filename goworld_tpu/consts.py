"""Centralized compile-time tunables.

Mirrors the role of the reference's ``engine/consts/consts.go:6-137``: every
magic number that shapes runtime behavior lives here so operators can audit
them in one place.
"""

# --- ticking ----------------------------------------------------------------
# Reference runs 5 ms ticks on game/gate/dispatcher (consts.go:36,46,57).
GAME_SERVICE_TICK_INTERVAL = 0.005  # seconds
GATE_SERVICE_TICK_INTERVAL = 0.005
DISPATCHER_SERVICE_TICK_INTERVAL = 0.005

# --- networking -------------------------------------------------------------
MAX_PACKET_SIZE = 25 * 1024 * 1024  # reference PacketConnection.go:23
SIZE_FIELD_SIZE = 4  # 4-byte little-endian length prefix
PAYLOAD_LEN_MASK = 0x7FFFFFFF  # high bit reserved (reference: compressed flag)
CONNECTION_WRITE_BUFFER_SIZE = 1024 * 1024  # consts.go:14-61
CONNECTION_READ_BUFFER_SIZE = 1024 * 1024
BUFFERED_IO_SIZE = 16 * 1024
FLUSH_INTERVAL = 0.005  # auto-flush cadence (GoWorldConnection.go:437-452)

# --- dispatcher queue bounds (consts.go:30-34) ------------------------------
ENTITY_PENDING_PACKET_QUEUE_MAX_LEN = 1000
GAME_PENDING_PACKET_QUEUE_MAX_LEN = 1_000_000
DISPATCHER_MESSAGE_QUEUE_LEN = 10_000

# --- timeouts ---------------------------------------------------------------
DISPATCHER_MIGRATE_TIMEOUT = 60.0  # consts.go (1 min migrate window)
DISPATCHER_LOAD_TIMEOUT = 60.0
# Freeze buffering window (reference: 10 s, consts.go FREEZE_GAME_TIMEOUT).
# A restarting game here is a fresh Python interpreter (~2-4 s import cost
# per game, restarted sequentially by the CLI); 10 s leaves no headroom on a
# loaded box and an expired block DROPS packets instead of buffering them.
DISPATCHER_FREEZE_GAME_TIMEOUT = 30.0
# Freeze fence: each dispatcher's ack is emitted on the same TCP stream
# strictly after it installs the block, so processing the N-th ack IS the
# proof that every pre-block packet has been processed (game/service.py
# main loop). The quiescence knobs below are only the SAFETY NET for the
# all-acks-never-arrive case (dead dispatcher), entered after
# FREEZE_ACK_TIMEOUT.
FREEZE_ACK_TIMEOUT = 10.0
FREEZE_QUIESCENT_WINDOW = 0.3
FREEZE_DRAIN_CAP = 5.0
RECONNECT_INTERVAL = 1.0  # DispatcherConnMgr reconnect backoff
CLIENT_HEARTBEAT_TIMEOUT = 30.0  # gate kills silent clients

# --- persistence ------------------------------------------------------------
DEFAULT_SAVE_INTERVAL = 300.0  # 5 min (read_config.go:28)

# --- AOI / TPU compute plane ------------------------------------------------
# Default fixed neighbor-set capacity per entity on the TPU path. The
# reference's go-aoi has no cap; interest sets in practice are bounded by
# design caps (e.g. 100 avatars/space, unity_demo/SpaceService.go:13-15).
AOI_MAX_NEIGHBORS = 128
# Default per-cell capacity of the spatial hash grid (padded, static shape).
AOI_CELL_CAPACITY = 64
# Default position-sync cadence (read_config.go:328,380 → 100 ms).
POSITION_SYNC_INTERVAL = 0.1

# --- debug switches ---------------------------------------------------------
DEBUG_PACKETS = False
DEBUG_SPACES = False
DEBUG_SAVE_LOAD = False
DEBUG_CLIENTS = False
DEBUG_MIGRATE = False

# --- supervisor start tags (binutil consts.go:133-137) ----------------------
# Printed once a process is serving; the CLI start command scans child logs
# for these to sequence dispatchers -> games -> gates.
DISPATCHER_STARTED_TAG = "SUPERVISOR: dispatcher started ok"
GAME_STARTED_TAG = "SUPERVISOR: game started ok"
GATE_STARTED_TAG = "SUPERVISOR: gate started ok"
FREEZED_TAG = "SUPERVISOR: game freezed"

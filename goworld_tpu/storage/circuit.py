"""Per-backend circuit breaker for the storage worker.

No reference analog: GoWorld's storageRoutine retries a failed save forever
at a fixed 1 s (storage.go:197-240), sleeping INSIDE the single serial
worker — one dead backend wedges every other entity's persistence. The
breaker bounds that: after ``failure_threshold`` consecutive failures the
circuit OPENS and the worker stops touching the backend (ops defer into a
byte-capped queue, storage/__init__.py); after ``cooldown`` seconds the
next op becomes a HALF-OPEN probe — success closes the circuit, failure
re-opens it for another cooldown.

State values (``storage_circuit_state`` gauge): 0 = closed, 1 = open,
2 = half-open.
"""

from __future__ import annotations

import threading
import time
from typing import Callable


class CircuitBreaker:
    CLOSED = 0
    OPEN = 1
    HALF_OPEN = 2

    def __init__(
        self,
        failure_threshold: int = 5,
        cooldown: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._lock = threading.Lock()
        self._clock = clock
        self.failure_threshold = failure_threshold
        self.cooldown = cooldown
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0

    def configure(self, failure_threshold: int, cooldown: float) -> None:
        with self._lock:
            self.failure_threshold = failure_threshold
            self.cooldown = cooldown

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May the caller attempt the backend right now? OPEN past the
        cooldown transitions to HALF_OPEN and admits one probe."""
        with self._lock:
            if self._state == self.OPEN:
                if self._clock() - self._opened_at >= self.cooldown:
                    self._state = self.HALF_OPEN
                    return True
                return False
            return True  # CLOSED, or HALF_OPEN (the probe is the caller)

    def record_success(self) -> None:
        with self._lock:
            self._state = self.CLOSED
            self._consecutive_failures = 0

    def record_failure(self) -> None:
        """A half-open probe failing re-opens immediately; a closed circuit
        opens at the consecutive-failure threshold."""
        with self._lock:
            self._consecutive_failures += 1
            if (self._state == self.HALF_OPEN
                    or self._consecutive_failures >= self.failure_threshold):
                self._state = self.OPEN
                self._opened_at = self._clock()

    def reset(self) -> None:
        with self._lock:
            self._state = self.CLOSED
            self._consecutive_failures = 0
            self._opened_at = 0.0

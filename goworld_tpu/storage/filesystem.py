"""Filesystem entity storage: one JSON file per entity.

Reference parity: ``engine/storage/backend/filesystem/filesystem.go:22-121``
— the simplest durable backend and the de-facto fake DB for local runs.
Layout: ``<dir>/<typename>$<eid>.json`` (reference uses the same flat-dir,
type-prefixed scheme). Writes go through a temp file + rename so a crash
mid-write never leaves a torn entity file.
"""

from __future__ import annotations

import json
import os
from typing import Optional


class FilesystemEntityStorage:
    def __init__(self, directory: str) -> None:
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, typename: str, eid: str) -> str:
        return os.path.join(self.directory, f"{typename}${eid}.json")

    def write(self, typename: str, eid: str, data: dict) -> None:
        path = self._path(typename, eid)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f)
        os.replace(tmp, path)

    def read(self, typename: str, eid: str) -> Optional[dict]:
        try:
            with open(self._path(typename, eid), encoding="utf-8") as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def exists(self, typename: str, eid: str) -> bool:
        return os.path.exists(self._path(typename, eid))

    def list_entity_ids(self, typename: str) -> list[str]:
        prefix = f"{typename}$"
        out = []
        for name in os.listdir(self.directory):
            if name.startswith(prefix) and name.endswith(".json"):
                out.append(name[len(prefix) : -len(".json")])
        return sorted(out)

    def close(self) -> None:
        pass

"""MySQL entity storage over the in-repo wire-protocol client.

Reference parity: ``engine/storage/backend/mysql/entity_storage_mysql.go``
— one row per entity in a shared table keyed (typename, eid), JSON data.
"""

from __future__ import annotations

import json
from typing import Optional

from goworld_tpu.netutil.mysql import MySQLClient, escape, parse_mysql_url

_TABLE = "gw_entities"


class MySQLEntityStorage:
    def __init__(self, url: str) -> None:
        self._client = MySQLClient(**parse_mysql_url(url))
        self._client.execute(
            f"CREATE TABLE IF NOT EXISTS {_TABLE} ("
            " typename VARCHAR(64) NOT NULL,"
            " eid CHAR(16) NOT NULL,"
            " data MEDIUMTEXT NOT NULL,"
            " PRIMARY KEY (typename, eid))"
        )

    def write(self, typename: str, eid: str, data: dict) -> None:
        self._client.execute(
            f"REPLACE INTO {_TABLE} VALUES ('{escape(typename)}', "
            f"'{escape(eid)}', '{escape(json.dumps(data))}')"
        )

    def read(self, typename: str, eid: str) -> Optional[dict]:
        rows = self._client.query(
            f"SELECT data FROM {_TABLE} WHERE typename='{escape(typename)}'"
            f" AND eid='{escape(eid)}'"
        )
        return json.loads(rows[0][0]) if rows else None

    def exists(self, typename: str, eid: str) -> bool:
        rows = self._client.query(
            f"SELECT 1 FROM {_TABLE} WHERE typename='{escape(typename)}'"
            f" AND eid='{escape(eid)}'"
        )
        return bool(rows)

    def list_entity_ids(self, typename: str) -> list[str]:
        rows = self._client.query(
            f"SELECT eid FROM {_TABLE} WHERE typename='{escape(typename)}'"
            f" ORDER BY eid"
        )
        return [r[0] for r in rows]

    def close(self) -> None:
        self._client.close()

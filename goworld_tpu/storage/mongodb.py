"""MongoDB entity storage over the in-repo OP_MSG client.

Reference parity: ``engine/storage/backend/mongodb/mongodb.go`` — one
collection per entity type, one document per entity (``_id`` = entity id,
``data`` = the attr document).
"""

from __future__ import annotations

from typing import Optional

from goworld_tpu.netutil.mongo import MongoClient, parse_mongo_url


class MongoEntityStorage:
    def __init__(self, url: str, db: str = "goworld") -> None:
        self._client = MongoClient(**parse_mongo_url(url))
        self._db = db

    def write(self, typename: str, eid: str, data: dict) -> None:
        self._client.upsert(
            self._db, typename, {"_id": eid}, {"_id": eid, "data": data}
        )

    def read(self, typename: str, eid: str) -> Optional[dict]:
        doc = self._client.find_one(self._db, typename, {"_id": eid})
        return None if doc is None else doc.get("data", {})

    def exists(self, typename: str, eid: str) -> bool:
        return self._client.find_one(self._db, typename, {"_id": eid}) is not None

    def list_entity_ids(self, typename: str) -> list[str]:
        docs = self._client.find(self._db, typename, {}, projection={"_id": 1})
        return sorted(d["_id"] for d in docs)

    def close(self) -> None:
        self._client.close()

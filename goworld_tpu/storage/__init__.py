"""Entity persistence: one ordered op queue + one storage worker.

Reference parity: ``engine/storage/storage.go:23-286`` — all storage
operations go through a single serial queue drained by one worker
(storageRoutine), so saves/loads for one entity never race; completion
callbacks are posted back to the main loop. Backend SPI mirrors
``storage_common.go:6-13``: write/read/exists/list.

Resilience deviation (PR 3 — the reference retries a failed save FOREVER at
a fixed 1 s inside the single worker, wedging every other entity's
persistence behind one sick backend): save retries back off exponentially
(``[storage] retry_base_interval`` → ``retry_max_interval``), and a
per-backend **circuit breaker** (storage/circuit.py) opens after
``circuit_failure_threshold`` consecutive failures. While the circuit is
open, saves defer into a byte-capped FIFO (``deferred_bytes_cap``,
drop-oldest, counted on ``storage_dropped_ops_total``) and the worker keeps
serving other ops; after ``circuit_cooldown`` the next save probes the
backend half-open and a success flushes the deferred queue in order.
Observability: ``storage_circuit_state`` (0 closed / 1 open / 2 half-open),
``storage_retries_total``, ``storage_deferred_bytes``.

Backends: filesystem (one JSON file per entity, the reference's de-facto
"fake DB" for local runs, filesystem.go:22-121), sqlite (stdlib), and the
reference's three network backends — redis, mongodb, mysql — over in-repo
wire-protocol clients (netutil/{resp,mongo,mysql}.py; no drivers).
"""

from __future__ import annotations

import collections
import json
import time
from typing import Callable, Deque, Optional

from goworld_tpu import consts, telemetry
from goworld_tpu.storage.circuit import CircuitBreaker
from goworld_tpu.utils import async_jobs, gwlog, opmon, post

_GROUP = "storage"

_backend = None
_breaker = CircuitBreaker(
    failure_threshold=consts.STORAGE_CIRCUIT_FAILURE_THRESHOLD,
    cooldown=consts.STORAGE_CIRCUIT_COOLDOWN,
)
_retry_base = consts.STORAGE_RETRY_BASE_INTERVAL
_retry_max = consts.STORAGE_RETRY_MAX_INTERVAL
_deferred_cap = consts.STORAGE_DEFERRED_BYTES_CAP


class _SaveOp:
    __slots__ = ("typename", "eid", "data", "callback", "nbytes", "trace")

    def __init__(self, typename: str, eid: str, data: dict,
                 callback: Optional[Callable]) -> None:
        self.typename = typename
        self.eid = eid
        self.data = data
        self.callback = callback
        # Sampled TraceContext active when the save was QUEUED (e.g. a
        # traced RPC calling entity.save()): the backend write records a
        # storage.save span under it, even though the write lands later
        # on the worker thread (tracing's ring is thread-safe).
        self.trace = telemetry.tracing.current()
        try:
            self.nbytes = len(json.dumps(data, default=str))
        except Exception:
            self.nbytes = len(repr(data))


# Saves awaiting a closed circuit, oldest first (order matters: a newer
# save of the same entity must never be overwritten by a replayed older
# one, so _run_save flushes this queue before touching a fresh op).
_deferred: Deque[_SaveOp] = collections.deque()
_deferred_bytes = 0

_STATE = telemetry.gauge(
    "storage_circuit_state",
    "Storage circuit breaker: 0=closed 1=open 2=half-open.")
_STATE.set_function(lambda: _breaker.state)
_RETRIES = telemetry.counter(
    "storage_retries_total", "Failed storage save attempts (each retry).")
_DEFERRED_BYTES_G = telemetry.gauge(
    "storage_deferred_bytes",
    "Bytes of save ops deferred while the storage circuit is open.")
_DEFERRED_BYTES_G.set_function(lambda: _deferred_bytes)
_DROPPED_OPS = telemetry.counter(
    "storage_dropped_ops_total",
    "Deferred save ops dropped before reaching the backend.", ("reason",))


def initialize(storage_config) -> None:
    """Create the backend from a StorageConfig (read_config.go [storage])
    and configure the retry/circuit knobs."""
    global _backend, _retry_base, _retry_max, _deferred_cap
    _backend = make_backend(storage_config.type, storage_config)
    _retry_base = getattr(storage_config, "retry_base_interval",
                          consts.STORAGE_RETRY_BASE_INTERVAL)
    _retry_max = getattr(storage_config, "retry_max_interval",
                         consts.STORAGE_RETRY_MAX_INTERVAL)
    _deferred_cap = getattr(storage_config, "deferred_bytes_cap",
                            consts.STORAGE_DEFERRED_BYTES_CAP)
    _breaker.configure(
        getattr(storage_config, "circuit_failure_threshold",
                consts.STORAGE_CIRCUIT_FAILURE_THRESHOLD),
        getattr(storage_config, "circuit_cooldown",
                consts.STORAGE_CIRCUIT_COOLDOWN),
    )
    _breaker.reset()


def make_backend(kind: str, cfg):
    if kind == "filesystem":
        from goworld_tpu.storage.filesystem import FilesystemEntityStorage

        return FilesystemEntityStorage(cfg.directory)
    if kind == "sqlite":
        from goworld_tpu.storage.sqlite import SQLiteEntityStorage

        return SQLiteEntityStorage(cfg.directory)
    if kind == "redis":
        from goworld_tpu.storage.redis import RedisEntityStorage

        return RedisEntityStorage(cfg.url)
    if kind == "redis_cluster":
        from goworld_tpu.storage.redis_cluster import RedisClusterEntityStorage

        return RedisClusterEntityStorage(list(cfg.start_nodes))
    if kind == "mongodb":
        from goworld_tpu.storage.mongodb import MongoEntityStorage

        return MongoEntityStorage(cfg.url, db=getattr(cfg, "db", "goworld"))
    if kind == "mysql":
        from goworld_tpu.storage.mysql import MySQLEntityStorage

        return MySQLEntityStorage(cfg.url)
    raise ValueError(
        f"unknown storage type {kind!r} "
        f"(available: filesystem, sqlite, redis, redis_cluster, mongodb, mysql)"
    )


def set_backend(backend) -> None:
    """Swap the backend (tests / embedded use): a fresh backend means a
    fresh circuit — deferred ops targeting the OLD backend are discarded."""
    global _backend, _deferred_bytes
    _backend = backend
    if _deferred:
        gwlog.warnf("storage: discarding %d deferred save op(s) on backend swap",
                    len(_deferred))
        _deferred.clear()
        _deferred_bytes = 0
    _breaker.reset()


def get_backend():
    return _backend


def initialized() -> bool:
    return _backend is not None


# --- async API (storage.go:66-130) ------------------------------------------


def save(typename: str, eid: str, data: dict, callback: Optional[Callable] = None) -> None:
    """Queue a save. Retries back off up to ``retry_max_interval``; once the
    circuit opens the op defers (byte-capped) instead of blocking the
    worker. ``callback(None, err)`` fires when the write lands (err None)
    or the op is dropped (err set)."""
    op = _SaveOp(typename, eid, data, callback)
    async_jobs.append_job(_GROUP, lambda: _run_save(op), None)


def _run_save(op: _SaveOp) -> None:
    """Worker-thread entry for one save: older deferred ops flush first
    (per-entity write order must hold across circuit transitions)."""
    _flush_deferred()
    if _deferred or not _breaker.allow():
        # Circuit (still) open, or older ops are still queued behind it.
        _defer(op)
        return
    _write_with_retries(op)


def _flush_deferred() -> None:
    while _deferred:
        if not _breaker.allow():
            return
        op = _pop_deferred()
        if not _write_with_retries(op):
            return  # circuit re-opened; op went back to the queue front


def _write_with_retries(op: _SaveOp) -> bool:
    """Attempt the write with capped exponential backoff; K consecutive
    failures open the circuit and park the op at the deferred-queue FRONT
    (it is the oldest unwritten op). Returns True once written."""
    delay = _retry_base
    while True:
        try:
            mon = opmon.Operation("storage.save")
            t0 = time.monotonic()
            _backend.write(op.typename, op.eid, op.data)
            mon.finish(warn_threshold=1.0)  # storage.go:194,234
            if op.trace is not None:
                tr = telemetry.tracing
                tr.record_span(
                    "storage.save", t0, time.monotonic() - t0,
                    op.trace.trace_id, tr.new_span_id(), op.trace.span_id,
                    {"typename": op.typename, "eid": op.eid,
                     "bytes": op.nbytes})
            _breaker.record_success()
            _complete(op, None)
            return True
        except Exception as e:  # noqa: BLE001
            _breaker.record_failure()
            _RETRIES.inc()
            if _breaker.state != CircuitBreaker.CLOSED:
                gwlog.errorf(
                    "storage: save %s.%s failed (%s); circuit OPEN — "
                    "deferring (probe in %.1fs)",
                    op.typename, op.eid, e, _breaker.cooldown)
                _defer(op, front=True)
                return False
            gwlog.errorf("storage: save %s.%s failed (%s); retrying in %.1fs",
                         op.typename, op.eid, e, delay)
            time.sleep(delay)
            delay = min(delay * 2.0, _retry_max)


def _defer(op: _SaveOp, front: bool = False) -> None:
    global _deferred_bytes
    if front:
        _deferred.appendleft(op)
    else:
        _deferred.append(op)
    _deferred_bytes += op.nbytes
    # Drop-OLDEST at the byte cap: the freshest save of an entity is the
    # one worth keeping. (A single op bigger than the whole cap is kept —
    # dropping it could never make room for itself.)
    while _deferred_bytes > _deferred_cap and len(_deferred) > 1:
        old = _pop_deferred()
        _DROPPED_OPS.labels("overflow").inc()
        _complete(old, RuntimeError(
            "storage deferred-queue overflow (circuit open)"))


def _pop_deferred() -> _SaveOp:
    global _deferred_bytes
    op = _deferred.popleft()
    _deferred_bytes -= op.nbytes
    return op


def _complete(op: _SaveOp, err: Optional[BaseException]) -> None:
    if op.callback is not None:
        post.post(lambda cb=op.callback, e=err: cb(None, e))


def _final_flush() -> None:
    """Last-chance drain at process exit (wait_clear): ONE attempt per
    deferred op, no sleeps — a still-dead backend must not stall the
    freeze/terminate path, so the remainder drops (counted, callbacks
    errored) the moment one write fails."""
    while _deferred:
        op = _pop_deferred()
        try:
            _backend.write(op.typename, op.eid, op.data)
            _breaker.record_success()
            _complete(op, None)
        except Exception as e:  # noqa: BLE001
            _breaker.record_failure()
            _DROPPED_OPS.labels("shutdown").inc()
            _complete(op, e)
            while _deferred:
                _DROPPED_OPS.labels("shutdown").inc()
                _complete(_pop_deferred(), e)
            gwlog.errorf(
                "storage: backend still failing at shutdown (%s); deferred "
                "saves dropped (bounded loss — see storage_dropped_ops_total)",
                e)
            return


def load(typename: str, eid: str, callback: Callable) -> None:
    async_jobs.append_job(_GROUP, lambda: _backend.read(typename, eid), _wrap(callback))


def exists(typename: str, eid: str, callback: Callable) -> None:
    async_jobs.append_job(_GROUP, lambda: _backend.exists(typename, eid), _wrap(callback))


def list_entity_ids(typename: str, callback: Callable) -> None:
    async_jobs.append_job(_GROUP, lambda: _backend.list_entity_ids(typename), _wrap(callback))


def _wrap(callback):
    if callback is None:
        return None
    return lambda result, err: callback(result, err)


def deferred_count() -> int:
    """Saves parked behind an open circuit (chaos harness / diagnostics)."""
    return len(_deferred)


def circuit_state() -> int:
    return _breaker.state


def wait_clear(timeout: float = 30.0) -> bool:
    """Drain the op queue (storage.go:118-121). Circuit-deferred saves
    stay deferred — they are waiting on the BACKEND, not the worker; use
    :func:`drain_for_shutdown` on the process-exit path."""
    return async_jobs.wait_clear(timeout)


def drain_for_shutdown(timeout: float = 30.0) -> bool:
    """Terminate path: drain the queue AND give circuit-deferred saves one
    last no-sleep probe each — a healed backend gets the data, a dead one
    drops it (bounded, counted loss) without stalling process exit."""
    if _deferred:
        async_jobs.append_job(_GROUP, _final_flush, None)
    return async_jobs.wait_clear(timeout)


class SyncStorageAdapter:
    """Synchronous facade bound to the module backend; plugs into
    ``entity_manager.Runtime.storage`` for in-process use and tests."""

    def save(self, typename: str, eid: str, data: dict) -> None:
        if _backend is not None:
            _backend.write(typename, eid, data)

    def load(self, typename: str, eid: str) -> Optional[dict]:
        if _backend is None:
            return None
        return _backend.read(typename, eid)

    def exists(self, typename: str, eid: str) -> bool:
        return _backend is not None and _backend.exists(typename, eid)

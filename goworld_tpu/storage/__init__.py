"""Entity persistence: one ordered op queue + one storage worker.

Reference parity: ``engine/storage/storage.go:23-286`` — all storage
operations go through a single serial queue drained by one worker
(storageRoutine), so saves/loads for one entity never race; saves retry
forever (:165-286); completion callbacks are posted back to the main loop.
Backend SPI mirrors ``storage_common.go:6-13``: write/read/exists/list.

Backends: filesystem (one JSON file per entity, the reference's de-facto
"fake DB" for local runs, filesystem.go:22-121), sqlite (stdlib), and the
reference's three network backends — redis, mongodb, mysql — over in-repo
wire-protocol clients (netutil/{resp,mongo,mysql}.py; no drivers).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from goworld_tpu.utils import async_jobs, gwlog, opmon

_GROUP = "storage"
_SAVE_RETRY_INTERVAL = 1.0

_backend = None


def initialize(storage_config) -> None:
    """Create the backend from a StorageConfig (read_config.go [storage])."""
    global _backend
    _backend = make_backend(storage_config.type, storage_config)


def make_backend(kind: str, cfg):
    if kind == "filesystem":
        from goworld_tpu.storage.filesystem import FilesystemEntityStorage

        return FilesystemEntityStorage(cfg.directory)
    if kind == "sqlite":
        from goworld_tpu.storage.sqlite import SQLiteEntityStorage

        return SQLiteEntityStorage(cfg.directory)
    if kind == "redis":
        from goworld_tpu.storage.redis import RedisEntityStorage

        return RedisEntityStorage(cfg.url)
    if kind == "redis_cluster":
        from goworld_tpu.storage.redis_cluster import RedisClusterEntityStorage

        return RedisClusterEntityStorage(list(cfg.start_nodes))
    if kind == "mongodb":
        from goworld_tpu.storage.mongodb import MongoEntityStorage

        return MongoEntityStorage(cfg.url, db=getattr(cfg, "db", "goworld"))
    if kind == "mysql":
        from goworld_tpu.storage.mysql import MySQLEntityStorage

        return MySQLEntityStorage(cfg.url)
    raise ValueError(
        f"unknown storage type {kind!r} "
        f"(available: filesystem, sqlite, redis, redis_cluster, mongodb, mysql)"
    )


def set_backend(backend) -> None:
    global _backend
    _backend = backend


def get_backend():
    return _backend


def initialized() -> bool:
    return _backend is not None


# --- async API (storage.go:66-130) ------------------------------------------


def save(typename: str, eid: str, data: dict, callback: Optional[Callable] = None) -> None:
    """Queue a save; retries forever on error (storageRoutine :197-240)."""

    def routine():
        while True:
            try:
                op = opmon.Operation("storage.save")
                _backend.write(typename, eid, data)
                op.finish(warn_threshold=1.0)  # storage.go:194,234
                return None
            except Exception as e:  # noqa: BLE001
                gwlog.errorf("storage: save %s.%s failed (%s); retrying", typename, eid, e)
                time.sleep(_SAVE_RETRY_INTERVAL)

    async_jobs.append_job(_GROUP, routine, _wrap(callback))


def load(typename: str, eid: str, callback: Callable) -> None:
    async_jobs.append_job(_GROUP, lambda: _backend.read(typename, eid), _wrap(callback))


def exists(typename: str, eid: str, callback: Callable) -> None:
    async_jobs.append_job(_GROUP, lambda: _backend.exists(typename, eid), _wrap(callback))


def list_entity_ids(typename: str, callback: Callable) -> None:
    async_jobs.append_job(_GROUP, lambda: _backend.list_entity_ids(typename), _wrap(callback))


def _wrap(callback):
    if callback is None:
        return None
    return lambda result, err: callback(result, err)


def wait_clear(timeout: float = 30.0) -> bool:
    """Drain the op queue (terminate/freeze path, storage.go:118-121)."""
    return async_jobs.wait_clear(timeout)


class SyncStorageAdapter:
    """Synchronous facade bound to the module backend; plugs into
    ``entity_manager.Runtime.storage`` for in-process use and tests."""

    def save(self, typename: str, eid: str, data: dict) -> None:
        if _backend is not None:
            _backend.write(typename, eid, data)

    def load(self, typename: str, eid: str) -> Optional[dict]:
        if _backend is None:
            return None
        return _backend.read(typename, eid)

    def exists(self, typename: str, eid: str) -> bool:
        return _backend is not None and _backend.exists(typename, eid)

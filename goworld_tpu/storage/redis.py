"""Redis entity storage over the in-repo RESP2 client.

Reference parity: ``engine/storage/backend/redis/entity_storage_redis.go``
— entities serialize to one value per key. Key scheme
``gwes:<typename>$<eid>`` (reference uses the same type-prefixed flat
space); values are JSON like the filesystem backend, so entities can be
migrated between backends with a copy loop.
"""

from __future__ import annotations

import json
from typing import Optional

from goworld_tpu.netutil.resp import RespClient, parse_redis_url

_PREFIX = "gwes:"


class RedisEntityStorage:
    def __init__(self, url: str) -> None:
        self._client = RespClient(**parse_redis_url(url))

    @staticmethod
    def _key(typename: str, eid: str) -> str:
        return f"{_PREFIX}{typename}${eid}"

    def write(self, typename: str, eid: str, data: dict) -> None:
        self._client.set(self._key(typename, eid), json.dumps(data))

    def read(self, typename: str, eid: str) -> Optional[dict]:
        raw = self._client.get(self._key(typename, eid))
        return None if raw is None else json.loads(raw)

    def exists(self, typename: str, eid: str) -> bool:
        return self._client.exists(self._key(typename, eid))

    def list_entity_ids(self, typename: str) -> list[str]:
        prefix = f"{_PREFIX}{typename}$"
        keys = self._client.scan_keys(prefix + "*")
        return sorted(k[len(prefix):] for k in keys)

    def close(self) -> None:
        self._client.close()

"""Redis Cluster entity storage.

Reference parity:
``engine/storage/backend/redis_cluster/entity_storage_redis_cluster.go:1``
— identical contract, key scheme and JSON values as the single-node redis
backend (so either can read the other's data after a migration copy loop),
routed through the cluster client: MOVED/ASK slot redirects per key,
list_entity_ids scans every master.
"""

from __future__ import annotations

from typing import Optional

from goworld_tpu.netutil.resp_cluster import RespClusterClient
from goworld_tpu.storage.redis import RedisEntityStorage


class RedisClusterEntityStorage(RedisEntityStorage):
    """All method bodies inherited — only the client construction differs
    (both clients expose the same get/set/exists/scan_keys surface)."""

    def __init__(
        self, start_nodes: list[str], password: Optional[str] = None
    ) -> None:
        self._client = RespClusterClient(start_nodes, password=password)

"""SQLite entity storage (stdlib).

Fills the reference's SQL-backend slot (``engine/storage/backend/mysql/
entity_storage_mysql.go``) without an external server: same schema shape —
one row per entity keyed by (typename, entityid) with a JSON document column.
All access happens on the single storage worker, so one connection with
``check_same_thread=False`` is safe.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
from typing import Optional


class SQLiteEntityStorage:
    def __init__(self, directory: str, filename: str = "entities.sqlite") -> None:
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, filename)
        self._conn = sqlite3.connect(
            self.path, check_same_thread=False, timeout=10.0
        )
        self._lock = threading.Lock()
        with self._lock:
            # WAL + busy_timeout: every game process in a deployment shares
            # this file (see kvdb/sqlite.py).
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA busy_timeout=10000")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS entities ("
                " typename TEXT NOT NULL, eid TEXT NOT NULL, data TEXT NOT NULL,"
                " PRIMARY KEY (typename, eid))"
            )
            self._conn.commit()

    def write(self, typename: str, eid: str, data: dict) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO entities (typename, eid, data) VALUES (?, ?, ?)"
                " ON CONFLICT(typename, eid) DO UPDATE SET data = excluded.data",
                (typename, eid, json.dumps(data)),
            )
            self._conn.commit()

    def read(self, typename: str, eid: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT data FROM entities WHERE typename = ? AND eid = ?",
                (typename, eid),
            ).fetchone()
        return json.loads(row[0]) if row else None

    def exists(self, typename: str, eid: str) -> bool:
        with self._lock:
            row = self._conn.execute(
                "SELECT 1 FROM entities WHERE typename = ? AND eid = ?",
                (typename, eid),
            ).fetchone()
        return row is not None

    def list_entity_ids(self, typename: str) -> list[str]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT eid FROM entities WHERE typename = ? ORDER BY eid",
                (typename,),
            ).fetchall()
        return [r[0] for r in rows]

    def close(self) -> None:
        with self._lock:
            self._conn.close()

"""Entity / client identifier generation.

Reference parity: ``engine/uuid/uuid.go:15-59`` — Mongo-ObjectId-style 12-byte
ids (4B timestamp + 5B machine/pid + 3B counter) encoded with a custom 64-char
alphabet into exactly 16 characters — and ``engine/common/types.go:8-47`` which
defines EntityID/ClientID as 16-char strings.

``gen_fixed_entity_id`` reproduces the deterministic "nil space" id scheme
(reference: engine/entity/space_ops.go:32-46 uses ``GenFixedUUID(gameid)`` so
every process can compute any game's nil-space id without coordination).
"""

from __future__ import annotations

import hashlib
import os
import secrets
import threading
import time

# Type aliases (ids travel as str on the wire, like the reference's string types).
EntityID = str
ClientID = str
GateID = int
GameID = int
DispatcherID = int

ENTITYID_LENGTH = 16
CLIENTID_LENGTH = 16

# 64-char URL-safe alphabet: 12 raw bytes → 16 chars, 6 bits per char.
_ALPHABET = "0123456789abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ-_"

_machine = secrets.token_bytes(3)
_pid = os.getpid() & 0xFFFF
_counter_lock = threading.Lock()
_counter = secrets.randbelow(1 << 24)


def _reseed_after_fork() -> None:
    """Forked children must not replay the parent's id sequence."""
    global _machine, _pid, _counter
    _machine = secrets.token_bytes(3)
    _pid = os.getpid() & 0xFFFF
    _counter = secrets.randbelow(1 << 24)


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reseed_after_fork)


def _encode12(raw: bytes) -> str:
    """Encode exactly 12 bytes into 16 chars (6 bits each)."""
    assert len(raw) == 12
    n = int.from_bytes(raw, "big")
    out = []
    for shift in range(90, -6, -6):
        out.append(_ALPHABET[(n >> shift) & 0x3F])
    return "".join(out)


def gen_entity_id() -> EntityID:
    """Generate a globally-unique 16-char entity id."""
    global _counter
    with _counter_lock:
        _counter = (_counter + 1) & 0xFFFFFF
        c = _counter
    ts = int(time.time()) & 0xFFFFFFFF
    raw = (
        ts.to_bytes(4, "big")
        + _machine
        + _pid.to_bytes(2, "big")
        + c.to_bytes(3, "big")
    )
    return _encode12(raw)


def gen_client_id() -> ClientID:
    return gen_entity_id()


def gen_fixed_entity_id(key: int | str) -> EntityID:
    """Deterministic 16-char id derived only from ``key``.

    Used for per-game nil spaces so any process can address game N's nil space
    without a lookup (reference: space_ops.go:32-46, uuid.go GenFixedUUID).
    """
    digest = hashlib.sha256(f"goworld_tpu-fixed-{key}".encode()).digest()[:12]
    return _encode12(digest)


def is_entity_id(s: object) -> bool:
    return (
        isinstance(s, str)
        and len(s) == ENTITYID_LENGTH
        and all(ch in _ALPHABET for ch in s)
    )

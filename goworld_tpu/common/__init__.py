"""Common identifier types, hashing and small collections.

Reference parity: ``engine/common`` (types.go:8-47, collections.go,
entityid_set.go, hash.go:13-57) and ``engine/uuid`` (uuid.go:15-59).
"""

from goworld_tpu.common.entity_id import (
    ENTITYID_LENGTH,
    CLIENTID_LENGTH,
    EntityID,
    ClientID,
    GateID,
    GameID,
    DispatcherID,
    gen_entity_id,
    gen_client_id,
    gen_fixed_entity_id,
    is_entity_id,
)
from goworld_tpu.common.hashing import hash_string, hash_entity_id

__all__ = [
    "ENTITYID_LENGTH",
    "CLIENTID_LENGTH",
    "EntityID",
    "ClientID",
    "GateID",
    "GameID",
    "DispatcherID",
    "gen_entity_id",
    "gen_client_id",
    "gen_fixed_entity_id",
    "is_entity_id",
    "hash_string",
    "hash_entity_id",
]

"""Stable string hashing used for shard routing.

Reference parity: ``engine/common/hash.go:13-57`` (LevelDB-style hash used for
service shard-by-key) and ``engine/dispatchercluster/hash.go:7-12`` (EntityID →
dispatcher routing uses the *last two bytes* of the id so that an entity's
traffic always transits the same dispatcher, giving per-entity FIFO ordering).

Python's builtin ``hash`` is salted per-process, so we implement a fixed FNV-1a
variant: routing decisions must agree across processes.
"""

from __future__ import annotations


def hash_string(s: str) -> int:
    """Deterministic 32-bit hash of a string (FNV-1a)."""
    h = 0x811C9DC5
    for b in s.encode("utf-8"):
        h ^= b
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


def hash_entity_id(eid: str) -> int:
    """Hash an entity id for dispatcher selection.

    Mirrors the reference's scheme of using the trailing bytes of the id
    (dispatchercluster/hash.go:7-12): ids share a timestamp/machine prefix, so
    the tail carries the entropy.
    """
    tail = eid[-4:]
    h = 0
    for ch in tail:
        h = (h * 64 + ord(ch)) & 0x7FFFFFFF
    return h

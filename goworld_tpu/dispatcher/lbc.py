"""Load-balancing heap over game CPU reports.

Reference parity: ``components/dispatcher/lbcheap.go:11-78`` — a min-heap of
per-game CPU%; ``chooseGame`` pops the least-loaded game and nudges its load
by +0.1 so repeated picks within one report interval spread out
(DispatcherService.go:529-542,947-957).
"""

from __future__ import annotations

import heapq


class LBCHeap:
    """Min-heap of (cpu_percent, gameid) with lazy invalidation."""

    def __init__(self) -> None:
        self._heap: list[list] = []  # [cpu, gameid, valid]
        self._entries: dict[int, list] = {}

    def update(self, gameid: int, cpu_percent: float) -> None:
        old = self._entries.get(gameid)
        if old is not None:
            old[2] = False
        entry = [cpu_percent, gameid, True]
        self._entries[gameid] = entry
        heapq.heappush(self._heap, entry)
        # Lazy-deletion compaction: periodic reports would otherwise grow the
        # heap without bound when choose() is rarely called.
        if len(self._heap) > 2 * len(self._entries) + 16:
            self._heap = [e for e in self._heap if e[2]]
            heapq.heapify(self._heap)

    def remove(self, gameid: int) -> None:
        old = self._entries.pop(gameid, None)
        if old is not None:
            old[2] = False

    def choose(self) -> int | None:
        """Pop the least-loaded game and re-push with +0.1 nudge
        (lbcheap.go:72-78)."""
        while self._heap:
            cpu, gameid, valid = self._heap[0]
            if not valid or self._entries.get(gameid) is not self._heap[0]:
                heapq.heappop(self._heap)
                continue
            self.update(gameid, cpu + 0.1)
            return gameid
        return None

    def __len__(self) -> int:
        return len(self._entries)

    def validate(self) -> None:
        """Debug-mode invariant check (lbcheap.go:53-71)."""
        for gameid, entry in self._entries.items():
            assert entry[2], f"entry for game {gameid} marked invalid"
            assert entry[1] == gameid

"""Dispatcher process: the star-topology packet router.

Reference parity: ``components/dispatcher`` (SURVEY.md §2.2) — every game and
gate connects to every dispatcher; all cross-process traffic transits a
dispatcher chosen by EntityID hash, which gives per-entity FIFO ordering.
"""

from goworld_tpu.dispatcher.service import DispatcherService

__all__ = ["DispatcherService", "run"]


def run(dispid: int | None = None) -> int:
    """Process entry point (dispatcher.go:32-74)."""
    import argparse
    import asyncio

    from goworld_tpu.config import get as get_config, set_config_file
    from goworld_tpu.utils import gwlog

    parser = argparse.ArgumentParser(description="goworld_tpu dispatcher process")
    parser.add_argument("-dispid", type=int, default=dispid or 1)
    parser.add_argument("-configfile", type=str, default="")
    parser.add_argument("-log", type=str, default="")
    parser.add_argument("-d", action="store_true", help="daemonize")
    args, _ = parser.parse_known_args()
    if args.configfile:
        set_config_file(args.configfile)
    cfg = get_config()
    disp_cfg = cfg.dispatchers.get(args.dispid)
    if args.d:
        from goworld_tpu.utils.binutil import daemonize

        daemonize((disp_cfg.log_file if disp_cfg else None)
                  or f"dispatcher{args.dispid}.daemon.log")
    gwlog.setup(
        level=(args.log or (disp_cfg.log_level if disp_cfg else "info")),
        logfile=(disp_cfg.log_file if disp_cfg else None) or None,
        fmt=cfg.log.format,
    )
    gwlog.set_source(f"dispatcher{args.dispid}")
    from goworld_tpu.telemetry import tracing

    tracing.configure_from_config(cfg.telemetry)

    async def main() -> int:
        import signal

        svc = DispatcherService(
            args.dispid,
            desired_games=cfg.deployment.desired_games,
            desired_gates=cfg.deployment.desired_gates,
            peer_heartbeat_timeout=cfg.cluster.peer_heartbeat_timeout,
            sync_flush_bytes=cfg.cluster.sync_flush_bytes,
            rebalance=cfg.rebalance,
        )
        host, port = (disp_cfg.host, disp_cfg.port) if disp_cfg else ("127.0.0.1", 0)
        # [cluster] transport = uds: serve a Unix-domain listener beside
        # TCP; co-located games/gates dial the path derived from the port.
        await svc.start(host, port,
                        uds_dir=(cfg.cluster.uds_dir
                                 if cfg.cluster.transport == "uds" else None))
        from goworld_tpu.utils import debug_http
        from goworld_tpu.utils.debug_http import setup_http_server

        debug_srv = await setup_http_server(disp_cfg.http_addr if disp_cfg else "")
        # Cluster observability plane: the DRIVER dispatcher (the same
        # process that plans rebalancing) hosts the ClusterCollector —
        # a loopback scrape of every configured http_addr, aggregated as
        # GET /cluster on this debug port (telemetry/collector.py;
        # rendered live by `python -m goworld_tpu.tools.gwtop`).
        collector = None
        if (cfg.telemetry.cluster_snapshot_interval > 0
                and args.dispid == cfg.rebalance.driver_dispatcher
                and disp_cfg is not None and disp_cfg.http_addr):
            from goworld_tpu.telemetry.collector import (
                ClusterCollector,
                http_targets_from_config,
            )

            targets = http_targets_from_config(cfg)
            if targets:
                collector = ClusterCollector(
                    targets,
                    interval=cfg.telemetry.cluster_snapshot_interval,
                    slo=cfg.slo)
                await collector.start()
                debug_http.set_cluster_provider(collector.view)
                gwlog.infof(
                    "cluster collector: aggregating %d processes on "
                    "/cluster every %.1fs%s", len(targets),
                    collector.interval,
                    " (SLO budgets active)" if cfg.slo.enabled() else "")
        # Black-box history ring (telemetry/history.py).
        hist_writer = None
        hist_task = None
        if cfg.telemetry.history_dir:
            import os as _os

            from goworld_tpu.telemetry import history as history_mod

            hist_writer = history_mod.HistoryWriter(
                _os.path.join(cfg.telemetry.history_dir,
                              f"dispatcher{args.dispid}"),
                f"dispatcher{args.dispid}",
                interval=cfg.telemetry.history_interval,
                segment_bytes=cfg.telemetry.history_segment_bytes,
                segments=cfg.telemetry.history_segments,
                health=svc._health)
            history_mod.set_active_writer(hist_writer)
            hist_task = asyncio.get_running_loop().create_task(
                hist_writer.run())
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        try:
            loop.add_signal_handler(signal.SIGTERM, stop.set)
        except (NotImplementedError, RuntimeError):
            pass
        await stop.wait()
        if hist_task is not None:
            hist_task.cancel()
        if hist_writer is not None:
            from goworld_tpu.telemetry import history as history_mod

            hist_writer.close()
            history_mod.clear_active_writer(hist_writer)
        if collector is not None:
            debug_http.clear_cluster_provider(collector.view)
            await collector.stop()
        if debug_srv is not None:
            await debug_srv.stop()
        await svc.stop()
        return 0

    return asyncio.run(main())

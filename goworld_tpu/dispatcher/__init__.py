"""Dispatcher process: the star-topology packet router.

Reference parity: ``components/dispatcher`` (SURVEY.md §2.2) — every game and
gate connects to every dispatcher; all cross-process traffic transits a
dispatcher chosen by EntityID hash, which gives per-entity FIFO ordering.
"""

from goworld_tpu.dispatcher.service import DispatcherService

__all__ = ["DispatcherService"]

"""``python -m goworld_tpu.dispatcher`` — dispatcher process binary."""

import sys

from goworld_tpu.dispatcher import run

sys.exit(run())

"""The dispatcher message loop: entity-table routing with blocking queues.

Reference parity: ``components/dispatcher/DispatcherService.go`` —

- ``entityDispatchInfos: {EntityID → (gameid, blockUntil, pendingQueue)}``
  (:28-32,184): written on NOTIFY_CREATE_ENTITY / REAL_MIGRATE / SET_GAME_ID,
  erased on NOTIFY_DESTROY_ENTITY and game-down cleanup (:643-661,627-640).
- Blocking semantics (:34-80): per-entity blockUntil + bounded pending queue
  during load/migrate; per-game bounded queue while a game is frozen (:82-169).
- Load-balanced choose-game = CPU-min-heap for anywhere-creates (:529-542);
  round-robin over non-banned games for boot entities (:545-555).
- Client→server position syncs are aggregated per target game and flushed per
  5 ms tick (:786-824).
- Deployment-ready barrier when desired counts connect (:446-476).
- kvreg replication (:734-748); freeze handshake (:478-494); reconnect
  reconciliation rejecting entities whose home moved (:376-398).

Concurrency model mirrors the reference: per-connection recv tasks feed one
logic queue drained by a single task — no locks in routing logic.
"""

from __future__ import annotations

import asyncio
import collections
import os
import struct
import time
from typing import Deque, Optional

import numpy as np

from goworld_tpu import consts, telemetry
from goworld_tpu.dispatcher.lbc import LBCHeap
from goworld_tpu.netutil.packet import Packet
from goworld_tpu.netutil.packet_conn import ConnectionClosed, PacketConnection
from goworld_tpu.proto.conn import (
    DELTA_SYNC_RECORD_SIZE,
    SYNC_DTYPE,
    SYNC_RECORD_SIZE,
    GoWorldConnection,
)
from goworld_tpu.proto.msgtypes import PROTO_VERSION, MsgType, is_gate_redirect
from goworld_tpu.telemetry import tracing
from goworld_tpu.utils import gwlog

_CLIENT_SYNC_BLOCK = 16 + SYNC_RECORD_SIZE  # [clientid + record] (downstream)
_CLIENT_DELTA_BLOCK = 16 + DELTA_SYNC_RECORD_SIZE  # v6 delta variant

# Records-per-packet amortization made visible (ISSUE 6): the whole point
# of batch routing is that one packet carries MANY records — these count
# records at the dispatcher seam so /metrics shows the ratio directly
# (dir="up" = client→game position syncs, dir="down" = game→gate fan-out
# blocks). Families are process-wide; children resolve per instance.
_SYNC_RECORDS = telemetry.counter(
    "dispatcher_sync_records_total",
    "Position-sync records routed through the dispatcher, by direction.",
    ("dispid", "dir"))
# Wall seconds spent in each hop of the sync fan-out pipeline (game pack →
# dispatcher route → gate demux → client write); bench.py --fanout turns
# deltas of these into the per-hop shares in its headline JSON.
_HOP_SECONDS = telemetry.counter(
    "fanout_hop_seconds_total",
    "Busy wall seconds per sync fan-out hop (game_collect|game_pack|"
    "game_send|dispatcher_route|gate_demux|client_write).",
    ("hop",))
_HOP_ROUTE = _HOP_SECONDS.labels("dispatcher_route")
# Migration routing events at the dispatcher seam: routed = REAL_MIGRATE
# forwarded to its target game, bounced = target game dead so the payload
# went HOME to the source game instead of dropping (the zero-loss clause),
# cancel = CANCEL_MIGRATE unblocked an entity's stream. The multigame
# bench reads these for its done/rolled-back headline.
_MIGRATE_EVENTS = telemetry.counter(
    "dispatcher_migrates_total",
    "Migration routing events (routed|bounced|cancel) per dispatcher.",
    ("dispid", "kind"))


class _EntityDispatchInfo:
    """Routing record for one entity (DispatcherService.go:28-80)."""

    __slots__ = ("gameid", "block_until", "pending")

    def __init__(self, gameid: int = 0) -> None:
        self.gameid = gameid
        self.block_until = 0.0
        self.pending: Deque[tuple[int, Packet]] = collections.deque()

    def blocked(self, now: float) -> bool:
        return self.block_until > now

    def block(self, now: float, duration: float) -> None:
        self.block_until = now + duration

    def unblock(self) -> None:
        self.block_until = 0.0

    def push_pending(self, msgtype: int, packet: Packet) -> bool:
        if len(self.pending) >= consts.ENTITY_PENDING_PACKET_QUEUE_MAX_LEN:
            return False
        self.pending.append((msgtype, packet))
        return True


class _GameInfo:
    """Per-game connection state (DispatcherService.go:82-169,180-182)."""

    def __init__(self, gameid: int) -> None:
        self.gameid = gameid
        self.proxy: Optional[GoWorldConnection] = None
        self.is_banned_boot = False
        self.block_until = 0.0  # frozen / reconnect window
        self.pending: Deque[tuple[int, Packet]] = collections.deque()

    @property
    def connected(self) -> bool:
        return self.proxy is not None and not self.proxy.closed

    def blocked(self, now: float) -> bool:
        return self.block_until > now

    def dispatch(self, msgtype: int, packet: Packet, now: float) -> None:
        if self.connected and not self.blocked(now):
            self.proxy.send(msgtype, packet)
        elif self.blocked(now):
            if len(self.pending) < consts.GAME_PENDING_PACKET_QUEUE_MAX_LEN:
                self.pending.append((msgtype, packet))
        # else: game is gone and not frozen — drop (reference handleGameDown)

    def unblock_and_flush(self) -> None:
        self.block_until = 0.0
        if self.proxy is None:
            return
        while self.pending:
            msgtype, packet = self.pending.popleft()
            self.proxy.send(msgtype, packet)


class _GateInfo:
    """Per-gate connection state with a reconnect-grace buffer.

    No reference analog: GoWorld's gate EXITS on dispatcher loss, so a gate
    never reconnects and the dispatcher can forget it instantly. Here a
    gate link blip is expected steady-state — during the grace window
    gate-bound packets buffer (bounded) and NOTIFY_GATE_DISCONNECTED is
    withheld, because broadcasting it would make every game detach the
    LIVE gate's client bindings."""

    def __init__(self, gateid: int) -> None:
        self.gateid = gateid
        self.proxy: Optional[GoWorldConnection] = None
        self.block_until = 0.0  # reconnect-grace window while down
        self.pending: Deque[tuple[int, Packet]] = collections.deque()
        # Boot generation announced at the gate's SET_GATE_ID handshake
        # (0 until one registers): /healthz reports it so the cluster
        # collector can cross-check every binding against the gate's own
        # announced generation (telemetry/collector.py summarize).
        self.generation = 0

    @property
    def connected(self) -> bool:
        return self.proxy is not None and not self.proxy.closed

    def blocked(self, now: float) -> bool:
        return self.block_until > now

    def dispatch(self, msgtype: int, packet: Packet, now: float) -> None:
        if self.connected:
            self.proxy.send(msgtype, packet)
        elif self.blocked(now):
            if len(self.pending) < consts.GAME_PENDING_PACKET_QUEUE_MAX_LEN:
                self.pending.append((msgtype, packet))
        # else: gate is gone for good — drop

    def unblock_and_flush(self) -> None:
        self.block_until = 0.0
        if self.proxy is None:
            return
        while self.pending:
            msgtype, packet = self.pending.popleft()
            self.proxy.send(msgtype, packet)


class DispatcherService:
    """One dispatcher process. Run with :meth:`start`, stop with :meth:`stop`."""

    def __init__(self, dispid: int, desired_games: int = 1, desired_gates: int = 1,
                 peer_heartbeat_timeout: Optional[float] = None,
                 sync_flush_bytes: Optional[int] = None,
                 rebalance=None) -> None:
        self.dispid = dispid
        self.desired_games = desired_games
        self.desired_gates = desired_gates
        # Size trigger for the position-sync aggregation buffers
        # ([cluster] sync_flush_bytes; 0 disables): a burst larger than
        # this flushes to its game IMMEDIATELY instead of sitting out the
        # rest of the 5 ms tick interval.
        self.sync_flush_bytes = (
            consts.DISPATCHER_SYNC_FLUSH_BYTES
            if sync_flush_bytes is None else sync_flush_bytes)
        # Liveness deadline for game/gate links ([cluster]
        # peer_heartbeat_timeout; 0 disables): HEARTBEAT is sent on idle
        # links and peers silent past the deadline are closed, converting
        # half-open connections into the peers' reconnect path.
        self.peer_heartbeat_timeout = (
            consts.CLUSTER_PEER_HEARTBEAT_TIMEOUT
            if peer_heartbeat_timeout is None else peer_heartbeat_timeout)
        self.entities: dict[str, _EntityDispatchInfo] = {}
        self.games: dict[int, _GameInfo] = {}
        self.gates: dict[int, _GateInfo] = {}
        # Not-yet-routed entities holding buffered packets: eid → expiry.
        # Gives a gate's ring replay racing the game's re-handshake into a
        # restarted dispatcher a grace window instead of a drop.
        self._unrouted: dict[str, float] = {}
        # Boot requests that arrived while NO boot-capable game had a live
        # link (flap / rolling restart): retried each tick until the grace
        # window lapses.
        self._pending_boots: list[tuple[Packet, float]] = []
        self.kvreg: dict[str, str] = {}
        # Whole-space handoffs (ISSUE 18) this dispatcher parked member
        # streams for: spaceid → (deadline, [parked eids]). Entries clear
        # on SPACE_MIGRATE_ACK (receiver restored), SPACE_MIGRATE_ABORT
        # (donor unfroze in place), or the deadline sweep.
        self._space_handoffs: dict[str, tuple[float, list]] = {}
        self.deployment_ready = False
        self._boot_rr = 0
        self._lbc = LBCHeap()
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: set = set()  # all live peer connections
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=consts.DISPATCHER_MESSAGE_QUEUE_LEN)
        self._tasks: list[asyncio.Task] = []
        # position-sync aggregation: gameid → bytearray of 32 B records
        self._pending_syncs: dict[int, bytearray] = {}
        # sender identity, populated at handshake (reference stores the id on
        # the connection proxy itself)
        self._proxy_games: dict[GoWorldConnection, int] = {}
        self._proxy_gates: dict[GoWorldConnection, int] = {}
        # Liveness bookkeeping: proxy → monotonic last-packet time (updated
        # by the per-connection recv task), proxy → sent_packets mark at
        # the last heartbeat tick (idle-link detection).
        self._peer_last_seen: dict[GoWorldConnection, float] = {}
        self._hb_sent_marks: dict[GoWorldConnection, int] = {}
        self._last_hb_tick = 0.0
        # Chaos/testing hook: while cleared, the logic and tick loops stop
        # draining — models a stalled (SIGSTOP-like) process whose sockets
        # stay open. pause()/resume().
        self._resume_event = asyncio.Event()
        self._resume_event.set()
        self._started_at = 0.0
        self.port: int = 0
        self._uds_server: Optional[asyncio.base_events.Server] = None
        self.uds_path: Optional[str] = None
        d = str(dispid)
        self._sync_records_up = _SYNC_RECORDS.labels(d, "up")
        self._sync_records_down = _SYNC_RECORDS.labels(d, "down")
        self._mig_routed = _MIGRATE_EVENTS.labels(d, "routed")
        self._mig_bounced = _MIGRATE_EVENTS.labels(d, "bounced")
        self._mig_cancel = _MIGRATE_EVENTS.labels(d, "cancel")
        # Plain mirrors of the counters above: harnesses sum these across
        # dispatcher OBJECTS (dead ones included) — the telemetry children
        # are unregistered at stop(), so family sums go backwards across a
        # restart.
        self.migrates_routed = 0
        self.migrates_bounced = 0
        self.migrates_cancelled = 0
        # Live rebalancer ([rebalance] ini section / RebalanceConfig):
        # every dispatcher keeps the report table (feeds game_load_score
        # and /healthz), the configured driver additionally PLANS.
        from goworld_tpu.config.read_config import RebalanceConfig
        from goworld_tpu.rebalance import RebalancePlanner

        self.rebalance_cfg = rebalance or RebalanceConfig()
        self.planner = RebalancePlanner(self.rebalance_cfg)
        self._last_plan = 0.0
        # Harness hook: pause/resume planning without reconstructing the
        # service (the multigame bench measures convergence from a known
        # t0; a paused planner still ingests reports).
        self._rebalance_active = True

    # --- lifecycle ----------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0,
                    uds_dir: Optional[str] = None) -> None:
        """Bind the TCP listener (always — port discovery and remote
        peers) and, when ``uds_dir`` is not None ([cluster] transport =
        uds), ALSO a Unix-domain listener whose path derives from the
        bound TCP port (uds_path_for) so co-located games/gates can dial
        it without extra configuration. Both listeners feed the same
        connection handler: framing, handshakes, heartbeats, and replay
        semantics are transport-identical."""
        self._server = await asyncio.start_server(self._on_conn, host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        if uds_dir is not None:
            from goworld_tpu.dispatchercluster.cluster import uds_path_for

            path = uds_path_for(self.port, uds_dir)
            try:
                os.unlink(path)  # stale socket from a dead predecessor
            except OSError:
                pass
            self._uds_server = await asyncio.start_unix_server(
                self._on_conn, path)
            self.uds_path = path
        self._started_at = time.monotonic()
        self._tasks.append(asyncio.get_running_loop().create_task(self._logic_loop()))
        self._tasks.append(asyncio.get_running_loop().create_task(self._tick_loop()))
        self._register_metrics()
        from goworld_tpu.utils import debug_http

        debug_http.set_health_provider(self._health)
        gwlog.infof("dispatcher %d listening on %s:%d%s", self.dispid, host,
                    self.port,
                    f" + uds {self.uds_path}" if self.uds_path else "")
        gwlog.infof(consts.DISPATCHER_STARTED_TAG)

    def _health(self) -> dict:
        """One JSON object for GET /healthz (chaos/ops liveness probes —
        no /metrics text parsing needed)."""
        now = time.monotonic()

        def age(proxy) -> Optional[float]:
            last = self._peer_last_seen.get(proxy)
            return round(now - last, 3) if last is not None else None

        return {
            "kind": "dispatcher",
            "id": self.dispid,
            "uptime_s": round(now - self._started_at, 3),
            "deployment_ready": self.deployment_ready,
            "queue_depth": self._queue.qsize(),
            "entities_routed": len(self.entities),
            "rebalance": {
                "enabled": self.rebalance_cfg.enabled,
                "driver": (self.rebalance_cfg.driver_dispatcher
                           == self.dispid),
                "planner_service": self.rebalance_cfg.planner_service,
                "last_result": self.planner.last_result,
                "reporting_games": self.planner.reports.games(),
                "space_handoffs": len(self._space_handoffs),
            },
            "games": {
                str(gid): {"connected": gi.connected,
                           "last_seen_age_s": age(gi.proxy)}
                for gid, gi in self.games.items()
            },
            "gates": {
                str(gid): {"connected": gt.connected,
                           "last_seen_age_s": age(gt.proxy),
                           "gen": gt.generation}
                for gid, gt in self.gates.items()
            },
        }

    def _register_metrics(self) -> None:
        """Pull-sampled gauges on /metrics, labeled by dispid. set_function
        costs the logic loop nothing — collection walks the tables only
        when a scraper asks (telemetry/metrics.py). Wire-level packet/byte
        counters live one layer down in proto/conn.py (net_*_total) so
        every transport this dispatcher speaks is counted uniformly."""
        from goworld_tpu import telemetry

        d = str(self.dispid)
        telemetry.gauge(
            "dispatcher_queue_depth",
            "Packets waiting in the dispatcher logic queue.", ("dispid",),
        ).labels(d).set_function(self._queue.qsize)
        telemetry.gauge(
            "dispatcher_pending_entities",
            "Entities currently blocked (load/migrate window) or holding "
            "buffered packets.", ("dispid",),
        ).labels(d).set_function(
            lambda: sum(
                1 for i in self.entities.values()
                if i.pending or i.blocked(time.monotonic())
            ))
        telemetry.gauge(
            "dispatcher_connections",
            "Live peer connections (games + gates + handshaking).",
            ("dispid",),
        ).labels(d).set_function(lambda: len(self._conns))
        telemetry.gauge(
            "dispatcher_entity_table_size",
            "Entries in the entity routing table.", ("dispid",),
        ).labels(d).set_function(lambda: len(self.entities))

    def _track_peer_gauge(self, peer: str) -> None:
        """Pull-sampled ``cluster_peer_last_seen_seconds{dispid,peer}``:
        seconds since the named peer's last packet (NaN once gone). One
        child per registered game/gate; removed on disconnect."""
        from goworld_tpu import telemetry

        def age() -> float:
            table = self.games if peer.startswith("game") else self.gates
            info = table.get(int(peer[4:]))
            proxy = info.proxy if info is not None else None
            last = self._peer_last_seen.get(proxy) if proxy is not None else None
            return time.monotonic() - last if last is not None else float("nan")

        telemetry.gauge(
            "cluster_peer_last_seen_seconds",
            "Seconds since the last packet from each registered peer.",
            ("dispid", "peer"),
        ).labels(str(self.dispid), peer).set_function(age)

    def _untrack_peer_gauge(self, peer: str) -> None:
        from goworld_tpu import telemetry

        fam = telemetry.family("cluster_peer_last_seen_seconds")
        if fam is not None:
            fam.remove(str(self.dispid), peer)

    def _unregister_metrics(self) -> None:
        from goworld_tpu import telemetry

        d = str(self.dispid)
        for name in ("dispatcher_queue_depth", "dispatcher_pending_entities",
                     "dispatcher_connections", "dispatcher_entity_table_size"):
            fam = telemetry.family(name)
            if fam is not None:
                fam.remove(d)
        fam = telemetry.family("dispatcher_sync_records_total")
        if fam is not None:
            for direction in ("up", "down"):
                fam.remove(d, direction)
        fam = telemetry.family("dispatcher_migrates_total")
        if fam is not None:
            for kind in ("routed", "bounced", "cancel"):
                fam.remove(d, kind)
        fam = telemetry.family("game_load_score")
        if fam is not None:
            for gid in self.planner.reports.games():
                fam.remove(str(gid))
        fam = telemetry.family("cluster_peer_last_seen_seconds")
        if fam is not None:
            for gid in list(self.games):
                fam.remove(d, f"game{gid}")
            for gid in list(self.gates):
                fam.remove(d, f"gate{gid}")

    async def stop(self) -> None:
        from goworld_tpu.utils import debug_http

        debug_http.clear_health_provider(self._health)
        self._unregister_metrics()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()
        if self._uds_server is not None:
            self._uds_server.close()
        if self._server is not None:
            self._server.close()
            # Close live connections BEFORE wait_closed(): since 3.12.1
            # Server.wait_closed() waits for connection handlers, which only
            # exit once their sockets close — closing after would deadlock.
            for proxy in list(self._conns):
                proxy.close()
            await self._server.wait_closed()
        if self._uds_server is not None:
            await self._uds_server.wait_closed()
            self._uds_server = None
            if self.uds_path is not None:
                try:
                    os.unlink(self.uds_path)
                except OSError:
                    pass
        for gi in self.games.values():
            if gi.proxy is not None:
                gi.proxy.close()
        for gt in self.gates.values():
            if gt.proxy is not None:
                gt.proxy.close()

    # --- connection handling -------------------------------------------------

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        proxy = GoWorldConnection(
            PacketConnection(reader, writer), trace_wire=True)
        self._conns.add(proxy)
        self._peer_last_seen[proxy] = time.monotonic()
        try:
            while True:
                msgtype, packet = await proxy.recv()
                self._peer_last_seen[proxy] = time.monotonic()
                await self._queue.put((proxy, msgtype, packet))
        except ConnectionClosed:
            await self._queue.put((proxy, -1, None))  # disconnect sentinel
        finally:
            self._conns.discard(proxy)
            self._peer_last_seen.pop(proxy, None)
            self._hb_sent_marks.pop(proxy, None)
            proxy.close()

    async def _logic_loop(self) -> None:
        queue = self._queue
        while True:
            # Drain the whole burst without yielding (the gate and game
            # loops batch the same way): routing cost then scales with
            # PACKETS handled back to back, and peer links are corked for
            # the span of the burst so N forwards to one game/gate leave
            # in ONE transport write at batch end — skipping the
            # FLUSH_INTERVAL timer the tracecat soak measured as the worst
            # per-hop latency. No awaits between cork and uncork, so the
            # tick loop's heartbeats can never interleave into a corked
            # span.
            batch = [await queue.get()]
            while True:
                try:
                    batch.append(queue.get_nowait())
                except asyncio.QueueEmpty:
                    break
            await self._resume_event.wait()  # chaos pause hook (no-op live)
            corked: list[GoWorldConnection] = []
            if len(batch) > 1:
                corked = [gi.proxy for gi in self.games.values()
                          if gi.connected]
                corked += [gt.proxy for gt in self.gates.values()
                           if gt.connected]
                for p in corked:
                    p.cork()
            try:
                for proxy, msgtype, packet in batch:
                    try:
                        if msgtype == -1:
                            self._handle_disconnect(proxy)
                        elif packet is not None and packet.trace is not None:
                            # Sampled packet: the handling span covers queue
                            # dwell (recv → here, its own child span — THE
                            # number the paper's routing path hides) +
                            # routing, and any forward inside re-attaches
                            # the trailer downstream.
                            scope = tracing.continue_from_packet(
                                packet, "dispatcher.route",
                                dwell_name="dispatcher.queue_dwell")
                            scope.args["msgtype"] = int(msgtype)
                            scope.args["dispid"] = self.dispid
                            records = self._record_count(msgtype, packet)
                            if records is not None:
                                scope.args["records"] = records
                            with scope:
                                self._handle(proxy, msgtype, packet)
                        else:
                            self._handle(proxy, msgtype, packet)
                    except Exception:
                        gwlog.trace_error(
                            "dispatcher %d: error handling msgtype %s",
                            self.dispid, msgtype)
            finally:
                for p in corked:
                    try:
                        p.uncork()
                    except Exception:
                        pass  # a dead link must not strand the others

    @staticmethod
    def _record_count(msgtype: int, packet: Packet) -> Optional[int]:
        """Sync records carried by this packet (None for non-sync types) —
        the ``records`` attribute on dispatcher.route spans."""
        if msgtype == MsgType.SYNC_POSITION_YAW_FROM_CLIENT:
            return packet.payload_len() // SYNC_RECORD_SIZE
        if msgtype == MsgType.SYNC_POSITION_YAW_ON_CLIENTS:
            return (packet.payload_len() - 2) // _CLIENT_SYNC_BLOCK
        if msgtype == MsgType.SYNC_POSITION_YAW_DELTA_ON_CLIENTS:
            return (packet.payload_len() - 3) // _CLIENT_DELTA_BLOCK
        return None

    async def _tick_loop(self) -> None:
        while True:
            await asyncio.sleep(consts.DISPATCHER_SERVICE_TICK_INTERVAL)
            await self._resume_event.wait()  # chaos pause hook (no-op live)
            self._send_pending_syncs()
            self._sweep_dead_frozen_games()
            self._sweep_dead_gates()
            self._sweep_unrouted_entities()
            self._sweep_space_handoffs()
            self._retry_pending_boots()
            self._heartbeat_tick()
            self._rebalance_tick()

    # --- rebalance driving (rebalance/planner.py) ----------------------------

    def rebalance_pause(self) -> None:
        self._rebalance_active = False

    def rebalance_resume(self) -> None:
        self._rebalance_active = True

    def _rebalance_tick(self) -> None:
        """One planning round per [rebalance] interval on the driver
        dispatcher: plan against live links + fresh reports, then command
        each donor game. A move's REBALANCE_MIGRATE rides the same
        buffered per-game dispatch as every other packet, so a game in a
        reconnect-grace window receives it after the handshake — or never,
        if it dies, which the planner's next round simply observes."""
        rb = self.rebalance_cfg
        if (not rb.enabled or self.dispid != rb.driver_dispatcher
                or not self._rebalance_active):
            return
        if rb.planner_service:
            # The sharded RebalancePlannerService plans instead; its
            # REBALANCE_PLAN pushes arrive at _handle_rebalance_plan.
            return
        now = self._now()
        if now - self._last_plan < rb.interval:
            return
        self._last_plan = now
        connected = {gid for gid, gi in self.games.items() if gi.connected}
        self._dispatch_plan(self.planner.plan(connected, now), now)

    def _dispatch_plan(self, plan: list, now: float) -> None:
        """Turn a planning round's Move/SpaceMove list into dispatcher
        commands toward each donor game."""
        from goworld_tpu.rebalance.planner import Move

        for move in plan:
            gi = self.games.get(move.from_game)
            if gi is None or not gi.connected:
                continue  # link dropped since planning; next round re-sees
            if isinstance(move, Move):
                p = Packet()
                p.append_entity_id(move.from_space)
                p.append_entity_id(move.to_space)
                p.append_uint16(move.to_game)
                p.append_uint16(move.count)
                gi.dispatch(MsgType.REBALANCE_MIGRATE, p, now)
            else:
                p = Packet()
                p.append_entity_id(move.spaceid)
                p.append_uint16(move.to_game)
                gi.dispatch(MsgType.REBALANCE_MIGRATE_SPACE, p, now)

    def _handle_rebalance_plan(self, proxy: GoWorldConnection,
                               packet: Packet) -> None:
        """A plan computed by the sharded RebalancePlannerService (planner
        failover, ISSUE 18). The dispatcher stays the authority on command
        DISPATCH: it validates the config gate and per-game liveness, so a
        stale service (e.g. one racing its own destruction after losing a
        registration race) cannot move entities on a cluster that turned
        rebalancing off."""
        from goworld_tpu.rebalance.planner import plan_from_wire

        plan = plan_from_wire(packet.read_data())
        rb = self.rebalance_cfg
        if not (rb.enabled and rb.planner_service
                and self._rebalance_active):
            gwlog.warnf(
                "dispatcher %d: dropping REBALANCE_PLAN (%d commands) — "
                "planner-service rebalancing not active here",
                self.dispid, len(plan))
            return
        self._dispatch_plan(plan, self._now())

    # --- chaos/testing hooks -------------------------------------------------

    def pause(self) -> None:
        """Stall the process without closing sockets: the logic and tick
        loops stop draining (recv tasks keep filling the bounded queue —
        kernel-level ACKs continue, exactly like a SIGSTOPped process).
        Peers' liveness watchdogs are expected to kill the silent links."""
        self._resume_event.clear()

    def resume(self) -> None:
        self._resume_event.set()

    # --- peer liveness (no reference analog; PR 3) ---------------------------

    def _peer_proxies(self) -> list[tuple[str, GoWorldConnection]]:
        peers = [
            (f"game{gid}", gi.proxy)
            for gid, gi in self.games.items() if gi.connected
        ]
        peers.extend(
            (f"gate{gid}", gt.proxy)
            for gid, gt in self.gates.items() if gt.connected
        )
        return peers

    def _heartbeat_tick(self) -> None:
        """Every timeout/3: HEARTBEAT every idle registered link, and close
        links silent past the timeout (the peer's reconnect loop takes it
        from there — a half-open link must not stall forever)."""
        timeout = self.peer_heartbeat_timeout
        if timeout <= 0:
            return
        now = self._now()
        if now - self._last_hb_tick < max(0.05, timeout / 3.0):
            return
        self._last_hb_tick = now
        for name, proxy in self._peer_proxies():
            last = self._peer_last_seen.get(proxy)
            if last is not None and now - last > timeout:
                gwlog.warnf(
                    "dispatcher %d: %s silent for %.1fs (> %.1fs heartbeat "
                    "deadline); closing half-open link",
                    self.dispid, name, now - last, timeout)
                proxy.close()
                continue
            if self._hb_sent_marks.get(proxy) == proxy.conn.sent_packets:
                try:
                    proxy.send_cluster_heartbeat()
                except Exception:
                    pass  # dying link; its recv task reports the disconnect
            self._hb_sent_marks[proxy] = proxy.conn.sent_packets

    def _sweep_dead_frozen_games(self) -> None:
        """A game that disconnected — frozen for a reload, or unplanned
        (which now gets a reconnect-grace buffer window too) — and never
        came back: once its window lapses, clean it up like any dead game
        (the reference only buffers for the freeze timeout,
        DispatcherService.go:82-169)."""
        now = self._now()
        for gameid, gi in list(self.games.items()):
            if gi.proxy is None and gi.block_until and not gi.blocked(now):
                gi.block_until = 0.0
                # Buffered REAL_MIGRATE / SPACE_MIGRATE_DATA payloads are
                # entities' (or a whole space's) LAST copies: bounce each
                # home before the buffer drops (the trailing source-gameid
                # makes this possible without the long-gone forwarding
                # proxy).
                for msgtype, packet in gi.pending:
                    if msgtype not in (MsgType.REAL_MIGRATE,
                                       MsgType.SPACE_MIGRATE_DATA):
                        continue
                    eid = packet.read_entity_id()
                    packet.set_read_pos(0)
                    if not self._bounce_migrate_home(
                            eid, packet,
                            self._real_migrate_source(packet), now,
                            msgtype=msgtype):
                        gwlog.errorf(
                            "dispatcher %d: %s of %s buffered "
                            "for dead game %d has no live source; "
                            "state dropped", self.dispid,
                            MsgType(msgtype).name, eid, gameid)
                gi.pending.clear()
                self._handle_game_down(gameid)

    def _sweep_dead_gates(self) -> None:
        """A gate whose reconnect-grace window lapsed is really dead: NOW
        broadcast NOTIFY_GATE_DISCONNECTED (games detach its clients) and
        forget it."""
        now = self._now()
        for gateid, gt in list(self.gates.items()):
            if gt.proxy is None and gt.block_until and not gt.blocked(now):
                self.gates.pop(gateid, None)
                self._untrack_peer_gauge(f"gate{gateid}")
                dropped = len(gt.pending)
                gt.pending.clear()
                p = Packet()
                p.append_uint16(gateid)
                p.append_uint32(0)  # gone entirely: every generation is dead
                self._broadcast_games(MsgType.NOTIFY_GATE_DISCONNECTED, p)
                gwlog.infof(
                    "dispatcher %d: gate %d never reconnected (%d buffered "
                    "packets dropped); declared dead", self.dispid, gateid,
                    dropped)

    def _sweep_unrouted_entities(self) -> None:
        """Drop buffered packets for entities no game claimed within the
        grace window (the packets raced a re-handshake that never came, or
        named a destroyed/bogus entity)."""
        if not self._unrouted:
            return
        now = self._now()
        for eid, expiry in list(self._unrouted.items()):
            if now < expiry:
                continue
            del self._unrouted[eid]
            info = self.entities.get(eid)
            if info is not None and info.gameid == 0:
                gwlog.warnf(
                    "dispatcher %d: dropping %d buffered packets for "
                    "never-routed entity %s", self.dispid,
                    len(info.pending), eid)
                del self.entities[eid]

    # --- dispatch helpers ----------------------------------------------------

    def _now(self) -> float:
        return time.monotonic()

    def _game(self, gameid: int) -> _GameInfo:
        gi = self.games.get(gameid)
        if gi is None:
            gi = self.games[gameid] = _GameInfo(gameid)
        return gi

    def _gate(self, gateid: int) -> _GateInfo:
        gt = self.gates.get(gateid)
        if gt is None:
            gt = self.gates[gateid] = _GateInfo(gateid)
        return gt

    def _entity(self, eid: str) -> _EntityDispatchInfo:
        info = self.entities.get(eid)
        if info is None:
            info = self.entities[eid] = _EntityDispatchInfo()
        return info

    def _gameid_of(self, proxy: GoWorldConnection) -> int:
        return self._proxy_games.get(proxy, 0)

    def _gateid_of(self, proxy: GoWorldConnection) -> int:
        return self._proxy_gates.get(proxy, 0)

    def _dispatch_to_entity(self, eid: str, msgtype: int, packet: Packet) -> None:
        """Route a packet by the entity table, honoring blocks
        (DispatcherService.go:34-80,826-844). An UNKNOWN entity gets a
        short buffered grace window instead of an instant drop (deviation
        from the reference): after a dispatcher restart, a gate's replay
        ring can legitimately land packets before the owning game's
        re-handshake installs the route — the handshake/NOTIFY_CREATE
        flush delivers them; _sweep_unrouted_entities drops unclaimed
        buffers when the window lapses."""
        now = self._now()
        info = self.entities.get(eid)
        if info is None or info.gameid == 0:
            if info is None:
                info = self._entity(eid)
            if eid not in self._unrouted:
                self._unrouted[eid] = (
                    now + consts.DISPATCHER_RECONNECT_BUFFER_WINDOW)
            if not info.push_pending(msgtype, packet):
                gwlog.warnf(
                    "dispatcher %d: unrouted-entity buffer overflow for %s "
                    "(msgtype %s dropped)", self.dispid, eid, msgtype)
            return
        if info.blocked(now):
            if not info.push_pending(msgtype, packet):
                gwlog.warnf("dispatcher %d: pending queue overflow for %s", self.dispid, eid)
            return
        self._game(info.gameid).dispatch(msgtype, packet, now)

    def _flush_entity_pending(self, info: _EntityDispatchInfo) -> None:
        now = self._now()
        info.unblock()
        while info.pending:
            msgtype, packet = info.pending.popleft()
            self._game(info.gameid).dispatch(msgtype, packet, now)

    def _broadcast_games(self, msgtype: int, packet: Packet, except_game: int = 0) -> None:
        now = self._now()
        for gid, gi in self.games.items():
            if gid != except_game:
                gi.dispatch(msgtype, packet, now)

    def _broadcast_gates(self, msgtype: int, packet: Packet) -> None:
        now = self._now()
        for gt in self.gates.values():
            gt.dispatch(msgtype, packet, now)

    # --- message handling ----------------------------------------------------

    def _handle(self, proxy: GoWorldConnection, msgtype: int, packet: Packet) -> None:
        if is_gate_redirect(msgtype):
            # Payload starts [u16 gateid][clientid...]; route on gateid
            # (DispatcherService.go:841-844).
            self._route_to_gate(msgtype, packet)
            return
        if msgtype == MsgType.SYNC_POSITION_YAW_ON_CLIENTS:
            t0 = time.perf_counter()
            self._sync_records_down.inc(
                (packet.payload_len() - 2) // _CLIENT_SYNC_BLOCK)
            self._route_to_gate(msgtype, packet)
            _HOP_ROUTE.inc(time.perf_counter() - t0)
            return
        if msgtype == MsgType.SYNC_POSITION_YAW_DELTA_ON_CLIENTS:
            # v6 quantized-delta sync: same gateid-prefix routing as the
            # full-precision stream (the extra quantize_bits byte rides
            # the payload untouched).
            t0 = time.perf_counter()
            self._sync_records_down.inc(
                (packet.payload_len() - 3) // _CLIENT_DELTA_BLOCK)
            self._route_to_gate(msgtype, packet)
            _HOP_ROUTE.inc(time.perf_counter() - t0)
            return
        if msgtype == MsgType.CALL_FILTERED_CLIENTS:
            self._broadcast_gates(msgtype, packet)
            return

        handler = self._HANDLERS.get(msgtype)
        if handler is None:
            gwlog.warnf("dispatcher %d: unhandled msgtype %s", self.dispid, msgtype)
            return
        handler(self, proxy, packet)

    def _route_to_gate(self, msgtype: int, packet: Packet) -> None:
        """Route a [u16 gateid]-prefixed packet, parsing the header ONCE:
        forwarding serializes the whole payload regardless of the read
        cursor, so the old read → set_read_pos(0) → re-parse dance was
        two parses per redirect packet for nothing. A gate in its
        reconnect-grace window buffers; an unknown gateid drops (as the
        reference)."""
        gt = self.gates.get(packet.read_uint16())
        if gt is not None:
            gt.dispatch(msgtype, packet, self._now())

    # --- handshakes ----------------------------------------------------------

    def _check_proto_version(
        self, proxy: GoWorldConnection, packet: Packet, peer: str
    ) -> bool:
        """Reject a handshake whose PROTO_VERSION trailer is absent or
        different — a mixed-version pair would otherwise mis-frame packets
        whose layouts changed (e.g. the migrate-nonce fields) and fail far
        from the cause (ADVICE r3). Pre-version peers send no trailer."""
        ver = packet.read_uint32() if packet.unread_len() >= 4 else 0
        if ver == PROTO_VERSION:
            return True
        gwlog.errorf(
            "dispatcher %d: %s speaks protocol version %d, this dispatcher "
            "speaks %d — deploy dispatchers and games/gates in lockstep "
            "(restart the cluster with one build); closing the connection",
            self.dispid, peer, ver, PROTO_VERSION,
        )
        proxy.close()
        return False

    def _handle_set_game_id(self, proxy: GoWorldConnection, packet: Packet) -> None:
        gameid = packet.read_uint16()
        is_reconnect = packet.read_bool()
        is_restore = packet.read_bool()
        is_ban_boot = packet.read_bool()
        entity_ids = packet.read_data()
        if not isinstance(entity_ids, list) or not all(
                isinstance(e, str) for e in entity_ids):
            # Parser contract (gwlint R3 / the schema fuzz): hostile or
            # corrupt payloads raise ValueError, never leak a TypeError
            # out of the reconciliation loop below.
            raise ValueError(
                f"SET_GAME_ID from game {gameid}: entity list is "
                f"{type(entity_ids).__name__}, expected list[str]")
        if not self._check_proto_version(proxy, packet, f"game {gameid}"):
            return
        if not is_reconnect and not is_restore:
            # A COLD-booted game (neither a surviving process re-dialing
            # nor a freeze restore) owns no prior entities: any routing
            # entries still homed to this gameid belong to a dead
            # incarnation (crash + recreate inside the reconnect-grace
            # window, before the down-sweep wiped them). Purge them now —
            # stale routes would otherwise forward RPCs and sync records
            # at a game that never heard of those entities.
            stale = [eid for eid, info in self.entities.items()
                     if info.gameid == gameid]
            for eid in stale:
                del self.entities[eid]
                self._unrouted.pop(eid, None)
            if stale:
                gwlog.warnf(
                    "dispatcher %d: game %d cold boot replaces a dead "
                    "incarnation; purged %d stale entity routes",
                    self.dispid, gameid, len(stale))
        gi = self._game(gameid)
        gi.proxy = proxy
        gi.is_banned_boot = is_ban_boot
        self._proxy_games[proxy] = gameid
        self._lbc.update(gameid, 0.0)
        self._track_peer_gauge(f"game{gameid}")

        # Reconnect reconciliation: reject entities homed elsewhere
        # (DispatcherService.go:376-398).
        rejected: list[str] = []
        now = self._now()
        for eid in entity_ids:
            info = self.entities.get(eid)
            if info is not None and info.gameid not in (0, gameid):
                rejected.append(eid)
            else:
                info = self._entity(eid)
                info.gameid = gameid
                # The game just proved this entity LIVES there: any migrate
                # block (whose REAL_MIGRATE died with the pre-restore
                # process) is stale — without this, a lost migration leaves
                # the entity's RPC stream buffered for the full 60 s window.
                if info.blocked(now) or info.pending:
                    self._flush_entity_pending(info)
        proxy.send_set_game_id_ack(
            online_games=sorted(
                gid for gid, g in self.games.items() if g.connected
            ),
            rejected_entity_ids=rejected,
            kvreg_map=dict(self.kvreg),
            deployment_ready=self.deployment_ready,
        )
        notify = Packet()
        notify.append_uint16(gameid)
        self._broadcast_games(MsgType.NOTIFY_GAME_CONNECTED, notify, except_game=gameid)
        gi.unblock_and_flush()
        self._check_deployment_ready()
        gwlog.infof(
            "dispatcher %d: game %d connected (reconnect=%s restore=%s, %d entities, %d rejected)",
            self.dispid, gameid, is_reconnect, is_restore, len(entity_ids), len(rejected),
        )

    def _handle_set_gate_id(self, proxy: GoWorldConnection, packet: Packet) -> None:
        gateid = packet.read_uint16()
        fresh = packet.read_bool()
        gen = packet.read_uint32()
        if not self._check_proto_version(proxy, packet, f"gate {gateid}"):
            return
        if fresh and gateid in self.gates:
            # A brand-new gate PROCESS replacing a registered predecessor
            # (crash + restart inside the reconnect-grace window): the old
            # process's client bindings are dead — no socket will ever
            # serve those clientids again. Tell the games to detach them
            # BEFORE registering the new proxy, and drop the buffered
            # packets (they address clients of the dead incarnation). The
            # broadcast names the NEW generation as valid, so a game that
            # processes it AFTER a new-generation client already connected
            # (cross-dispatcher ordering) cannot detach the live client. A
            # surviving gate re-dialing after a link blip sends
            # fresh=False and keeps its bindings + buffer.
            old = self.gates[gateid]
            dropped = len(old.pending)
            old.pending.clear()
            p = Packet()
            p.append_uint16(gateid)
            p.append_uint32(gen)
            self._broadcast_games(MsgType.NOTIFY_GATE_DISCONNECTED, p)
            gwlog.warnf(
                "dispatcher %d: gate %d is a FRESH process (gen %d); "
                "detached the dead predecessor's clients on all games "
                "(%d buffered packets dropped)", self.dispid, gateid, gen,
                dropped)
        gt = self._gate(gateid)
        gt.proxy = proxy
        gt.generation = gen
        gt.block_until = 0.0
        self._proxy_gates[proxy] = gateid
        self._track_peer_gauge(f"gate{gateid}")
        gt.unblock_and_flush()  # reconnect within the grace window
        self._check_deployment_ready()
        gwlog.infof("dispatcher %d: gate %d connected (fresh=%s)",
                    self.dispid, gateid, fresh)

    def _check_deployment_ready(self) -> None:
        """Readiness barrier (DispatcherService.go:446-476)."""
        if self.deployment_ready:
            return
        n_games = sum(1 for g in self.games.values() if g.connected)
        n_gates = sum(1 for g in self.gates.values() if g.connected)
        if n_games >= self.desired_games and n_gates >= self.desired_gates:
            self.deployment_ready = True
            p = Packet()
            self._broadcast_games(MsgType.NOTIFY_DEPLOYMENT_READY, p)
            gwlog.infof("dispatcher %d: deployment ready (%d games, %d gates)",
                        self.dispid, n_games, n_gates)

    # --- entity table ---------------------------------------------------------

    def _handle_notify_create_entity(self, proxy: GoWorldConnection, packet: Packet) -> None:
        eid = packet.read_entity_id()
        gameid = self._gameid_of(proxy)
        info = self._entity(eid)
        info.gameid = gameid
        self._flush_entity_pending(info)

    def _handle_notify_destroy_entity(self, proxy: GoWorldConnection, packet: Packet) -> None:
        eid = packet.read_entity_id()
        self.entities.pop(eid, None)

    # --- client lifecycle -----------------------------------------------------

    def _handle_notify_client_connected(self, proxy: GoWorldConnection, packet: Packet) -> None:
        """Gate announced a fresh client; choose a boot game round-robin
        over non-banned games (DispatcherService.go:545-555,663-667).

        No game available — every boot-capable game mid-reconnect (a link
        flap under load, a rolling restart) — used to DROP the boot
        forever: the client sat connected with no player until it gave
        up. Boots now buffer for the reconnect-grace window and retry
        each tick; only a window that lapses with still no game drops
        (with the same warn)."""
        gameid = self._choose_game_for_boot()
        if gameid == 0:
            self._pending_boots.append(
                (packet, self._now() + consts.DISPATCHER_RECONNECT_BUFFER_WINDOW))
            gwlog.warnf(
                "dispatcher %d: no game available for boot entity; "
                "buffering %.0fs for a game (re)connect", self.dispid,
                consts.DISPATCHER_RECONNECT_BUFFER_WINDOW)
            return
        boot_eid = Packet(packet.payload)  # peek boot eid: clientid(16)+u16+eid(16)
        boot_eid.read_client_id()
        boot_eid.read_uint16()
        eid = boot_eid.read_entity_id()
        info = self._entity(eid)
        info.gameid = gameid
        self._game(gameid).dispatch(MsgType.NOTIFY_CLIENT_CONNECTED, packet, self._now())

    def _retry_pending_boots(self) -> None:
        """Tick-driven retry of boots that arrived while no boot-capable
        game had a live link (see _handle_notify_client_connected)."""
        if not self._pending_boots:
            return
        now = self._now()
        pending = self._pending_boots
        self._pending_boots = []
        for packet, expiry in pending:
            if now >= expiry:
                gwlog.warnf(
                    "dispatcher %d: boot entity request expired with no "
                    "game available; dropped", self.dispid)
                continue
            self._handle_notify_client_connected(None, packet)  # type: ignore[arg-type]

    def _handle_notify_client_disconnected(self, proxy: GoWorldConnection, packet: Packet) -> None:
        packet.read_client_id()
        owner_eid = packet.read_entity_id()
        packet.set_read_pos(0)
        self._dispatch_to_entity(owner_eid, MsgType.NOTIFY_CLIENT_DISCONNECTED, packet)

    def _choose_game_for_boot(self) -> int:
        candidates = sorted(
            gid for gid, g in self.games.items() if g.connected and not g.is_banned_boot
        )
        if not candidates:
            return 0
        self._boot_rr = (self._boot_rr + 1) % len(candidates)
        return candidates[self._boot_rr]

    # --- RPC routing ----------------------------------------------------------

    def _handle_call_entity_method(self, proxy: GoWorldConnection, packet: Packet) -> None:
        eid = packet.read_entity_id()
        packet.set_read_pos(0)
        self._dispatch_to_entity(eid, MsgType.CALL_ENTITY_METHOD, packet)

    def _handle_call_entity_method_from_client(self, proxy: GoWorldConnection, packet: Packet) -> None:
        eid = packet.read_entity_id()
        packet.set_read_pos(0)
        self._dispatch_to_entity(eid, MsgType.CALL_ENTITY_METHOD_FROM_CLIENT, packet)

    def _handle_call_nil_spaces(self, proxy: GoWorldConnection, packet: Packet) -> None:
        except_game = packet.read_uint16()
        packet.set_read_pos(0)
        self._broadcast_games(MsgType.CALL_NIL_SPACES, packet, except_game=except_game)

    # --- create / load somewhere ----------------------------------------------

    def _handle_create_entity_somewhere(self, proxy: GoWorldConnection, packet: Packet) -> None:
        gameid = packet.read_uint16()
        packet.read_varstr()
        eid = packet.read_entity_id()
        packet.set_read_pos(0)
        if gameid == 0:
            gameid = self._lbc.choose() or self._choose_game_for_boot()
        if gameid == 0:
            gwlog.warnf("dispatcher %d: no game for CREATE_ENTITY_SOMEWHERE", self.dispid)
            return
        self._entity(eid).gameid = gameid
        self._game(gameid).dispatch(MsgType.CREATE_ENTITY_SOMEWHERE, packet, self._now())

    def _handle_load_entity_somewhere(self, proxy: GoWorldConnection, packet: Packet) -> None:
        gameid = packet.read_uint16()
        packet.read_varstr()
        eid = packet.read_entity_id()
        packet.set_read_pos(0)
        info = self.entities.get(eid)
        if info is not None and info.gameid != 0:
            return  # already loaded somewhere; calls will route there
        if gameid == 0:
            gameid = self._lbc.choose() or self._choose_game_for_boot()
        if gameid == 0:
            return
        info = self._entity(eid)
        info.gameid = gameid
        # Block RPCs while the entity loads (consts.go load timeout).
        info.block(self._now(), consts.DISPATCHER_LOAD_TIMEOUT)
        self._game(gameid).dispatch(MsgType.LOAD_ENTITY_SOMEWHERE, packet, self._now())

    # --- migration (DispatcherService.go:850-907) -----------------------------

    def _ack_requester(self, proxy: GoWorldConnection, msgtype: int, p: Packet) -> None:
        """Send a migration ack back to the requesting game THROUGH its
        buffered dispatch: a raw proxy write to a game that is mid-freeze
        lands in a socket its process never reads again, while the buffered
        path survives until the restore (a restored entity simply ignores a
        stale ack via _enter_space_request_valid)."""
        gameid = self._gameid_of(proxy)
        if gameid:
            self._game(gameid).dispatch(msgtype, p, self._now())
        else:
            proxy.send(msgtype, p)

    def _handle_query_space_gameid_for_migrate(self, proxy: GoWorldConnection, packet: Packet) -> None:
        spaceid = packet.read_entity_id()
        eid = packet.read_entity_id()
        nonce = packet.read_uint32()
        space_info = self.entities.get(spaceid)
        gameid = space_info.gameid if space_info is not None else 0
        # Ack goes back to the entity's current game (the requester); the
        # request nonce is echoed verbatim (proto/conn.py).
        p = Packet()
        p.append_entity_id(spaceid)
        p.append_entity_id(eid)
        p.append_uint16(gameid)
        p.append_uint32(nonce)
        self._ack_requester(proxy, MsgType.QUERY_SPACE_GAMEID_FOR_MIGRATE_ACK, p)

    def _handle_migrate_request(self, proxy: GoWorldConnection, packet: Packet) -> None:
        eid = packet.read_entity_id()
        spaceid = packet.read_entity_id()
        space_gameid = packet.read_uint16()
        nonce = packet.read_uint32()
        info = self._entity(eid)
        info.block(self._now(), consts.DISPATCHER_MIGRATE_TIMEOUT)
        p = Packet()
        p.append_entity_id(eid)
        p.append_entity_id(spaceid)
        p.append_uint16(space_gameid)
        p.append_uint32(nonce)
        self._ack_requester(proxy, MsgType.MIGRATE_REQUEST_ACK, p)

    @staticmethod
    def _real_migrate_source(packet: Packet) -> int:
        """Trailing u16 source gameid of a REAL_MIGRATE payload (0 when a
        pre-trailer build sent it) — readable without parsing the bson
        body, so sweep-time bounces need no proxy context."""
        payload = packet.payload
        if len(payload) < 20:  # eid(16) + target(2) + trailer(2)
            return 0
        return struct.unpack_from("<H", payload, len(payload) - 2)[0]

    def _handle_real_migrate(self, proxy: GoWorldConnection, packet: Packet) -> None:
        """Route the packed entity to its target game — or BOUNCE IT HOME.

        The packet carries the entity's entire state; the source game
        already destroyed its copy. Forwarding into a game that is gone
        would therefore destroy the entity's last copy — the exact loss
        the rebalancer's zero-loss contract forbids. Three target states:

        - connected / blocked (freeze or reconnect grace): route normally
          (gi.dispatch buffers through blocks);
        - UNKNOWN (no registration — e.g. THIS dispatcher restarted and a
          replayed REAL_MIGRATE raced the target's re-handshake): grant
          the target the standard reconnect-grace window and buffer; the
          handshake flush delivers, and _sweep_dead_frozen_games bounces
          any still-buffered payloads home if the window lapses;
        - declared DEAD (registered, link gone, grace over): bounce home
          now — the source game restores the entity in place (the
          migrator counts the bounce as a rollback)."""
        eid = packet.read_entity_id()
        target_game = packet.read_uint16()
        packet.set_read_pos(0)
        now = self._now()
        info = self._entity(eid)
        gi = self.games.get(target_game)
        if gi is None:
            gi = self._game(target_game)
            gi.block_until = now + consts.DISPATCHER_RECONNECT_BUFFER_WINDOW
            gwlog.warnf(
                "dispatcher %d: REAL_MIGRATE of %s targets unknown game "
                "%d; buffering %.0fs for its handshake", self.dispid, eid,
                target_game, consts.DISPATCHER_RECONNECT_BUFFER_WINDOW)
        elif not (gi.connected or gi.blocked(now)):
            source_game = (self._gameid_of(proxy)
                           or self._real_migrate_source(packet))
            if self._bounce_migrate_home(eid, packet, source_game, now):
                return
            gwlog.errorf(
                "dispatcher %d: REAL_MIGRATE of %s targets dead game %d "
                "and the source link is gone; entity state dropped",
                self.dispid, eid, target_game)
            self.entities.pop(eid, None)
            return
        info.gameid = target_game
        self._mig_routed.inc()
        self.migrates_routed += 1
        gi.dispatch(MsgType.REAL_MIGRATE, packet, now)
        self._flush_entity_pending(info)

    def _bounce_migrate_home(self, eid: str, packet: Packet,
                             source_game: int, now: float,
                             msgtype: int = MsgType.REAL_MIGRATE) -> bool:
        """Redirect a migrate payload (REAL_MIGRATE entity or
        SPACE_MIGRATE_DATA space bundle) back to its source game, which
        restores it in place. False if the source is gone too."""
        si = self.games.get(source_game) if source_game else None
        if si is None or not (si.connected or si.blocked(now)):
            return False
        gwlog.warnf(
            "dispatcher %d: %s of %s targets a dead game; "
            "bouncing home to game %d", self.dispid,
            MsgType(msgtype).name, eid, source_game)
        info = self._entity(eid)
        info.gameid = source_game
        self._mig_bounced.inc()
        self.migrates_bounced += 1
        si.dispatch(msgtype, packet, now)
        self._flush_entity_pending(info)
        return True

    def _handle_cancel_migrate(self, proxy: GoWorldConnection, packet: Packet) -> None:
        eid = packet.read_entity_id()
        info = self.entities.get(eid)
        if info is not None:
            self._mig_cancel.inc()
            self.migrates_cancelled += 1
            self._flush_entity_pending(info)

    # --- whole-space handoff (ISSUE 18; modelcheck space_handoff) -------------

    def _handle_space_migrate_prepare(self, proxy: GoWorldConnection,
                                      packet: Packet) -> None:
        """Donor game froze a space: park the LISTED member streams this
        dispatcher routes to the donor, then ack on the donor's own FIFO.

        Same fence contract as _handle_start_freeze_game: the ack is
        written strictly after the blocks, on the same stream as every
        packet already forwarded, so receiving it proves all of this
        dispatcher's pre-park traffic has been delivered to the donor —
        the pack after the last ack misses nothing.

        The list is the freeze-time membership, and only eids CURRENTLY
        routed to the donor park: a member that completed its own entity
        migrate before the freeze must not have its stream at the NEW
        game parked (modelcheck space_member_race found exactly this).

        A dead target game refuses the PREPARE outright — SPACE_MIGRATE_
        ABORT back to the donor, nothing parked — so the handoff fails in
        one hop instead of timing out against a corpse."""
        spaceid = packet.read_entity_id()
        to_game = packet.read_uint16()
        member_eids = packet.read_data()
        donor_game = self._gameid_of(proxy)
        now = self._now()
        tgt = self.games.get(to_game)
        if tgt is None or not (tgt.connected or tgt.blocked(now)):
            p = Packet()
            p.append_entity_id(spaceid)
            p.append_varstr("target_game_down")
            self._ack_requester(proxy, MsgType.SPACE_MIGRATE_ABORT, p)
            gwlog.warnf(
                "dispatcher %d: refusing SPACE_MIGRATE_PREPARE of %s — "
                "target game %d is dead", self.dispid, spaceid, to_game)
            return
        parked: list = []
        for eid in list(member_eids) + [spaceid]:
            info = self.entities.get(eid)
            if info is None or info.gameid != donor_game:
                continue  # moved or destroyed since the freeze snapshot
            info.block(now, consts.DISPATCHER_MIGRATE_TIMEOUT)
            parked.append(eid)
        self._space_handoffs[spaceid] = (
            now + consts.DISPATCHER_MIGRATE_TIMEOUT, parked)
        p = Packet()
        p.append_entity_id(spaceid)
        p.append_uint16(self.dispid)
        self._ack_requester(proxy, MsgType.SPACE_MIGRATE_PREPARE_ACK, p)

    def _handle_space_migrate_data(self, proxy: GoWorldConnection,
                                   packet: Packet) -> None:
        """Route the packed SPACE (with every member) to its target game —
        or bounce it home. Exactly REAL_MIGRATE's three-state contract,
        because the payload is the space's and members' last copy: route
        through blocks, grace-buffer for an unknown target's handshake,
        bounce home to the trailing source gameid when the target is
        declared dead."""
        spaceid = packet.read_entity_id()
        target_game = packet.read_uint16()
        packet.set_read_pos(0)
        now = self._now()
        info = self._entity(spaceid)
        gi = self.games.get(target_game)
        if gi is None:
            gi = self._game(target_game)
            gi.block_until = now + consts.DISPATCHER_RECONNECT_BUFFER_WINDOW
            gwlog.warnf(
                "dispatcher %d: SPACE_MIGRATE_DATA of %s targets unknown "
                "game %d; buffering %.0fs for its handshake", self.dispid,
                spaceid, target_game,
                consts.DISPATCHER_RECONNECT_BUFFER_WINDOW)
        elif not (gi.connected or gi.blocked(now)):
            source_game = (self._gameid_of(proxy)
                           or self._real_migrate_source(packet))
            if self._bounce_migrate_home(
                    spaceid, packet, source_game, now,
                    msgtype=MsgType.SPACE_MIGRATE_DATA):
                return
            gwlog.errorf(
                "dispatcher %d: SPACE_MIGRATE_DATA of %s targets dead "
                "game %d and the source link is gone; space state dropped",
                self.dispid, spaceid, target_game)
            self.entities.pop(spaceid, None)
            return
        info.gameid = target_game
        self._mig_routed.inc()
        self.migrates_routed += 1
        gi.dispatch(MsgType.SPACE_MIGRATE_DATA, packet, now)
        self._flush_entity_pending(info)

    def _handle_space_migrate_abort(self, proxy: GoWorldConnection,
                                    packet: Packet) -> None:
        """Donor broadcast: the handoff died (deadline, dead target, space
        destroyed) and the space unfroze in place — unpark every member."""
        spaceid = packet.read_entity_id()
        reason = packet.read_varstr()
        if self._release_space_handoff(spaceid):
            gwlog.infof(
                "dispatcher %d: space %s handoff aborted (%s); member "
                "streams unparked", self.dispid, spaceid, reason)

    def _handle_space_migrate_ack(self, proxy: GoWorldConnection,
                                  packet: Packet) -> None:
        """Receiver broadcast: the space restored. Member routes already
        moved with each NOTIFY_CREATE (which also flushed their streams);
        this clears the handoff entry and unparks any leftover parked eid
        (a member destroyed mid-handoff never gets a NOTIFY_CREATE)."""
        spaceid = packet.read_entity_id()
        packet.read_uint16()  # receiver gameid (logged at the receiver)
        self._release_space_handoff(spaceid)

    def _release_space_handoff(self, spaceid: str) -> bool:
        entry = self._space_handoffs.pop(spaceid, None)
        if entry is None:
            return False
        for eid in entry[1]:
            info = self.entities.get(eid)
            if info is not None:
                self._flush_entity_pending(info)
        return True

    def _sweep_space_handoffs(self) -> None:
        """Backstop: a handoff whose donor died before broadcasting an
        abort (or whose ack never reached us) must not park member streams
        past the migrate window — the deadline unparks unconditionally
        (modelcheck liveness: no stream stays parked forever)."""
        if not self._space_handoffs:
            return
        now = self._now()
        for spaceid, (deadline, _parked) in list(self._space_handoffs.items()):
            if now >= deadline:
                self._release_space_handoff(spaceid)
                gwlog.warnf(
                    "dispatcher %d: space %s handoff hit the dispatcher "
                    "deadline; member streams unparked", self.dispid,
                    spaceid)

    # --- position sync aggregation (DispatcherService.go:786-824) -------------

    def _handle_sync_position_yaw_from_client(self, proxy: GoWorldConnection, packet: Packet) -> None:
        """Demux one packet of concatenated 32 B records per destination
        game in ONE vectorized pass: a structured-array view over the
        payload, routing-table lookups per UNIQUE entity (not per record),
        and one boolean-mask ``tobytes`` per destination — the dispatcher's
        cost scales with packets and distinct entities, not records.
        Unknown / not-yet-routed entities drop, exactly like the legacy
        per-record loop (the parity oracle in tests/test_dispatcher.py
        pins batched == legacy on randomized streams); a trailing partial
        record is ignored. Per-game aggregation buffers flush on the 5 ms
        tick OR as soon as they exceed sync_flush_bytes, so a burst never
        sits out a full tick."""
        t0 = time.perf_counter()
        data = packet.payload
        k = len(data) // SYNC_RECORD_SIZE
        if not k:
            return
        self._sync_records_up.inc(k)
        entities = self.entities
        pending = self._pending_syncs
        now = self._now()
        if k == 1:
            info = entities.get(data[:16].decode("ascii"))
            if info is not None and info.gameid:
                if info.blocked(now):
                    # Migrate window: the route points at the game the
                    # entity is LEAVING. Park the record with the entity's
                    # pending queue; _flush_entity_pending delivers it to
                    # wherever REAL_MIGRATE (or a bounce) lands it — no
                    # record is ever delivered to a stale game.
                    info.push_pending(
                        MsgType.SYNC_POSITION_YAW_FROM_CLIENT,
                        Packet(data[:SYNC_RECORD_SIZE]))
                else:
                    buf = pending.setdefault(info.gameid, bytearray())
                    buf += data[:SYNC_RECORD_SIZE]
                    if self.sync_flush_bytes and len(buf) >= self.sync_flush_bytes:
                        self._flush_pending_sync(info.gameid)
            _HOP_ROUTE.inc(time.perf_counter() - t0)
            return
        arr = np.frombuffer(data, SYNC_DTYPE, count=k)
        uniq, inv = np.unique(arr["eid"], return_inverse=True)
        lut = np.empty(len(uniq), np.int32)
        blocked: list[tuple[int, _EntityDispatchInfo]] = []
        for j, eb in enumerate(uniq.tolist()):
            info = entities.get(eb.decode("ascii"))
            if info is None:
                lut[j] = 0
            elif info.gameid and info.blocked(now):
                # Steady state never takes this branch (blocked() is one
                # float compare per UNIQUE entity); records for migrating
                # entities divert to the per-entity pending queue below.
                lut[j] = 0
                blocked.append((j, info))
            else:
                lut[j] = info.gameid
        gameids = lut[inv]
        for gid in np.unique(lut).tolist():
            if gid == 0:
                continue  # unknown/unrouted entities drop (legacy semantics)
            buf = pending.setdefault(gid, bytearray())
            buf += arr[gameids == gid].tobytes()
            if self.sync_flush_bytes and len(buf) >= self.sync_flush_bytes:
                self._flush_pending_sync(gid)
        for j, info in blocked:
            info.push_pending(
                MsgType.SYNC_POSITION_YAW_FROM_CLIENT,
                Packet(arr[inv == j].tobytes()))
        _HOP_ROUTE.inc(time.perf_counter() - t0)

    def _flush_pending_sync(self, gameid: int) -> None:
        """Size-triggered early flush of one game's aggregation buffer."""
        buf = self._pending_syncs.pop(gameid, None)
        if buf:
            self._game(gameid).dispatch(
                MsgType.SYNC_POSITION_YAW_FROM_CLIENT, Packet(bytes(buf)),
                self._now())

    def _send_pending_syncs(self) -> None:
        if not self._pending_syncs:
            return
        now = self._now()
        for gameid, buf in self._pending_syncs.items():
            self._game(gameid).dispatch(
                MsgType.SYNC_POSITION_YAW_FROM_CLIENT, Packet(bytes(buf)), now
            )
        self._pending_syncs.clear()

    # --- kvreg (DispatcherService.go:734-748) ---------------------------------

    def _handle_kvreg_register(self, proxy: GoWorldConnection, packet: Packet) -> None:
        key = packet.read_varstr()
        value = packet.read_varstr()
        force = packet.read_bool()
        packet.set_read_pos(0)
        if value == "":
            # Deletion convention (ISSUE 18 planner failover): a forced
            # empty value POPS the key — the game-side reconcile must see
            # the shard as unclaimed, not as owned by "". Replicated so
            # every game's map drops it too.
            if force and key in self.kvreg:
                del self.kvreg[key]
                self._broadcast_games(MsgType.KVREG_REGISTER, packet)
            return
        if not force and key in self.kvreg:
            return  # first registration wins unless forced
        self.kvreg[key] = value
        self._broadcast_games(MsgType.KVREG_REGISTER, packet)

    # --- load balance / freeze ------------------------------------------------

    def _handle_heartbeat(self, proxy: GoWorldConnection, packet: Packet) -> None:
        """Liveness only: the recv task already refreshed last-seen."""

    def _handle_game_lbc_info(self, proxy: GoWorldConnection, packet: Packet) -> None:
        cpu = packet.read_float32()
        gameid = self._gameid_of(proxy)
        if gameid:
            self._lbc.update(gameid, cpu)

    def _handle_game_load_report(self, proxy: GoWorldConnection, packet: Packet) -> None:
        """Rich load report (rebalance/report.py schema): feeds the LBC
        choose-game heap (cpu, as GAME_LBC_INFO did), the planner's
        report table, and the game_load_score gauge."""
        from goworld_tpu import rebalance
        from goworld_tpu.rebalance.report import coerce_report, load_score

        # coerce_report validates shape + numeric fields (ValueError on
        # anything malformed — the wire-parser contract).
        report = coerce_report(packet.read_data())
        gameid = self._gameid_of(proxy)
        if not gameid:
            return
        self._lbc.update(gameid, float(report.get("cpu", 0.0)))
        self.planner.on_report(gameid, report, self._now())
        rebalance.LOAD_SCORE.labels(str(gameid)).set(load_score(report))

    def _handle_start_freeze_game(self, proxy: GoWorldConnection, packet: Packet) -> None:
        """Buffer the game's packets for the freeze window then ack
        (DispatcherService.go:478-494).

        FENCE CONTRACT (relied on by the game's freeze path): the ack is
        written to the SAME stream as every packet this dispatcher has
        forwarded to the game, strictly AFTER the block is installed, in
        the single logic task — game-bound sends here are synchronous
        transport writes, so there is no side queue the ack could
        overtake. Receiving this ack therefore proves all of this
        dispatcher's pre-block packets have been delivered."""
        gameid = self._gameid_of(proxy)
        if not gameid:
            return
        gi = self._game(gameid)
        gi.block_until = self._now() + consts.DISPATCHER_FREEZE_GAME_TIMEOUT
        proxy.send_start_freeze_game_ack()

    # --- disconnects ----------------------------------------------------------

    def _handle_disconnect(self, proxy: GoWorldConnection) -> None:
        gameid = self._proxy_games.pop(proxy, 0)
        if gameid:
            gi = self.games[gameid]
            if gi.proxy is not proxy:
                return  # stale disconnect: the game already reconnected
            gi.proxy = None
            self._untrack_peer_gauge(f"game{gameid}")
            if gi.blocked(self._now()):
                gwlog.infof("dispatcher %d: game %d down while frozen; buffering", self.dispid, gameid)
                return
            # Unplanned disconnect: a link blip, not necessarily a death.
            # Buffer like the freeze window (shorter) instead of instantly
            # wiping routes — the reconnect handshake flushes; the sweep
            # declares the game dead when the window lapses. (Deviation:
            # the reference declares game-down immediately,
            # DispatcherService.go:592-640.)
            gi.block_until = (
                self._now() + consts.DISPATCHER_RECONNECT_BUFFER_WINDOW)
            gwlog.warnf(
                "dispatcher %d: game %d link lost; buffering %.0fs for a "
                "reconnect", self.dispid, gameid,
                consts.DISPATCHER_RECONNECT_BUFFER_WINDOW)
            return
        gateid = self._proxy_gates.pop(proxy, 0)
        if gateid:
            gt = self.gates.get(gateid)
            if gt is None or gt.proxy is not proxy:
                return  # stale disconnect: the gate already reconnected
            gt.proxy = None
            self._untrack_peer_gauge(f"gate{gateid}")
            # Same grace window: broadcasting NOTIFY_GATE_DISCONNECTED for
            # a blip would make every game detach the live gate's client
            # bindings. _sweep_dead_gates broadcasts when the window
            # lapses without a reconnect.
            gt.block_until = (
                self._now() + consts.DISPATCHER_RECONNECT_BUFFER_WINDOW)
            gwlog.warnf(
                "dispatcher %d: gate %d link lost; buffering %.0fs for a "
                "reconnect", self.dispid, gateid,
                consts.DISPATCHER_RECONNECT_BUFFER_WINDOW)

    def _handle_game_down(self, gameid: int) -> None:
        """Unplanned game death: drop its routing entries, tell the others
        (DispatcherService.go:592-640)."""
        from goworld_tpu import rebalance

        self._lbc.remove(gameid)
        self.planner.on_game_down(gameid)
        rebalance.LOAD_SCORE.remove(str(gameid))
        dead = [eid for eid, info in self.entities.items() if info.gameid == gameid]
        for eid in dead:
            del self.entities[eid]
        p = Packet()
        p.append_uint16(gameid)
        self._broadcast_games(MsgType.NOTIFY_GAME_DISCONNECTED, p, except_game=gameid)
        self._purge_dead_game_services(gameid)
        gwlog.infof("dispatcher %d: game %d down, %d entities dropped", self.dispid, gameid, len(dead))

    def _purge_dead_game_services(self, gameid: int) -> None:
        """Release the dead game's service-shard claims (ISSUE 18 planner
        failover): pop every ``Service/…`` key it owned — and the
        ``/EntityID`` companion, or the reconcile would see a half-
        registered shard — and replicate the deletions so every surviving
        game's reconcile races to re-claim. Without this, a shard owned by
        a corpse stays claimed forever and its service (e.g. the
        RebalancePlannerService) never fails over."""
        from goworld_tpu.service import SERVICE_KVREG_PREFIX

        owner = f"game{gameid}"
        owned = [
            k for k, v in self.kvreg.items()
            if v == owner and k.startswith(SERVICE_KVREG_PREFIX)
            and "/" not in k[len(SERVICE_KVREG_PREFIX):]
        ]
        for k in owned:
            for key in (k, k + "/EntityID"):
                if self.kvreg.pop(key, None) is None:
                    continue
                p = Packet()
                p.append_varstr(key)
                p.append_varstr("")
                p.append_bool(True)
                self._broadcast_games(MsgType.KVREG_REGISTER, p,
                                      except_game=gameid)
        if owned:
            gwlog.warnf(
                "dispatcher %d: purged %d service shard claims of dead "
                "game %d (%s); survivors will re-claim", self.dispid,
                len(owned), gameid, ", ".join(sorted(owned)))

    _HANDLERS = {
        MsgType.SET_GAME_ID: _handle_set_game_id,
        MsgType.SET_GATE_ID: _handle_set_gate_id,
        MsgType.NOTIFY_CREATE_ENTITY: _handle_notify_create_entity,
        MsgType.NOTIFY_DESTROY_ENTITY: _handle_notify_destroy_entity,
        MsgType.NOTIFY_CLIENT_CONNECTED: _handle_notify_client_connected,
        MsgType.NOTIFY_CLIENT_DISCONNECTED: _handle_notify_client_disconnected,
        MsgType.CALL_ENTITY_METHOD: _handle_call_entity_method,
        MsgType.CALL_ENTITY_METHOD_FROM_CLIENT: _handle_call_entity_method_from_client,
        MsgType.CALL_NIL_SPACES: _handle_call_nil_spaces,
        MsgType.CREATE_ENTITY_SOMEWHERE: _handle_create_entity_somewhere,
        MsgType.LOAD_ENTITY_SOMEWHERE: _handle_load_entity_somewhere,
        MsgType.QUERY_SPACE_GAMEID_FOR_MIGRATE: _handle_query_space_gameid_for_migrate,
        MsgType.MIGRATE_REQUEST: _handle_migrate_request,
        MsgType.REAL_MIGRATE: _handle_real_migrate,
        MsgType.CANCEL_MIGRATE: _handle_cancel_migrate,
        MsgType.SPACE_MIGRATE_PREPARE: _handle_space_migrate_prepare,
        MsgType.SPACE_MIGRATE_DATA: _handle_space_migrate_data,
        MsgType.SPACE_MIGRATE_ABORT: _handle_space_migrate_abort,
        MsgType.SPACE_MIGRATE_ACK: _handle_space_migrate_ack,
        MsgType.REBALANCE_PLAN: _handle_rebalance_plan,
        MsgType.SYNC_POSITION_YAW_FROM_CLIENT: _handle_sync_position_yaw_from_client,
        MsgType.KVREG_REGISTER: _handle_kvreg_register,
        MsgType.GAME_LBC_INFO: _handle_game_lbc_info,
        MsgType.GAME_LOAD_REPORT: _handle_game_load_report,
        MsgType.START_FREEZE_GAME: _handle_start_freeze_game,
        MsgType.HEARTBEAT: _handle_heartbeat,
    }

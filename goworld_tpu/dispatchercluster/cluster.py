"""Real TCP cluster client: one managed connection per dispatcher.

Reference parity: ``engine/dispatchercluster/dispatcherclient/DispatcherConnMgr.go``
— each game/gate process keeps one auto-reconnecting connection per
dispatcher; on (re)connect it re-sends the handshake (SET_GAME_ID carrying
the live entity list, or SET_GATE_ID), then pumps received packets into the
process's logic queue via the delegate (:66-88,123-147). Reconnect backoff is
1 s (consts RECONNECT_INTERVAL).

While a connection is down, sends fall back to a buffering stub that drops
packets (the reference drops to dead dispatchers too; state re-syncs on the
reconnect handshake).
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional, Sequence

from goworld_tpu import consts
from goworld_tpu.dispatchercluster import DispatcherClusterBase, _NULL_SENDER
from goworld_tpu.netutil.packet import Packet
from goworld_tpu.netutil.packet_conn import ConnectionClosed, PacketConnection
from goworld_tpu.proto.conn import GoWorldConnection
from goworld_tpu.utils import gwlog

# Delegate signature: (dispatcher_index, msgtype, packet) — must be fast/non-blocking.
PacketHandler = Callable[[int, int, Packet], None]
# Handshake factory: given the fresh GoWorldConnection, performs the hello.
# Receives (dispatcher_index, proxy): the game handshake must send each
# dispatcher ONLY the entity ids it owns by hash (the reference's
# GetEntityIDsForDispatcher, DispatcherConnMgr.go:79) — a full list creates
# stale entries on non-owner dispatchers that later REJECT the entity at a
# restore after it migrated (its REAL_MIGRATE only updated the owner).
Handshaker = Callable[[int, GoWorldConnection], None]


class DispatcherConnMgr:
    """Managed connection to one dispatcher with auto-reconnect."""

    def __init__(
        self,
        index: int,
        addr: tuple[str, int],
        handshake: Handshaker,
        on_packet: PacketHandler,
        on_disconnect: Optional[Callable[[int], None]] = None,
    ) -> None:
        self.index = index
        self.addr = addr
        self._handshake = handshake
        self._on_packet = on_packet
        self._on_disconnect = on_disconnect
        self.proxy: Optional[GoWorldConnection] = None
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        self._connected_event = asyncio.Event()

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def wait_connected(self, timeout: float = 10.0) -> None:
        await asyncio.wait_for(self._connected_event.wait(), timeout)

    async def _run(self) -> None:
        """Connect → handshake → recv pump; repeat forever with backoff
        (DispatcherConnMgr.go:66-147)."""
        while not self._stopped:
            try:
                reader, writer = await asyncio.open_connection(*self.addr)
            except OSError:
                await asyncio.sleep(consts.RECONNECT_INTERVAL)
                continue
            proxy = GoWorldConnection(PacketConnection(reader, writer))
            self.proxy = proxy
            try:
                self._handshake(self.index, proxy)
                self._connected_event.set()
                while True:
                    msgtype, packet = await proxy.recv()
                    self._on_packet(self.index, msgtype, packet)
            except ConnectionClosed:
                pass
            except Exception:
                gwlog.trace_error("dispatcher conn %d: recv pump error", self.index)
            finally:
                self.proxy = None
                self._connected_event.clear()
                proxy.close()
                if self._on_disconnect is not None and not self._stopped:
                    self._on_disconnect(self.index)
            if not self._stopped:
                gwlog.warnf("dispatcher conn %d lost; reconnecting", self.index)
                await asyncio.sleep(consts.RECONNECT_INTERVAL)

    async def stop(self) -> None:
        self._stopped = True
        if self.proxy is not None:
            # Drain before close: the process exits right after stop() during
            # freeze/terminate, and packets still in the asyncio transport
            # buffer would be silently dropped — including REAL_MIGRATE of an
            # avatar that just migrated out, which then exists on NO game.
            try:
                await asyncio.wait_for(
                    self.proxy.conn.drain(hard=True), timeout=5.0
                )
            except Exception:
                pass  # peer already gone; nothing to preserve
            self.proxy.close()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass


class ClusterClient(DispatcherClusterBase):
    """The process-wide dispatcher fabric client (dispatchercluster.go:18-37)."""

    def __init__(
        self,
        addrs: Sequence[tuple[str, int]],
        handshake: Handshaker,
        on_packet: PacketHandler,
        on_disconnect: Optional[Callable[[int], None]] = None,
    ) -> None:
        self._mgrs = [
            DispatcherConnMgr(i, addr, handshake, on_packet, on_disconnect)
            for i, addr in enumerate(addrs)
        ]

    def start(self) -> None:
        for m in self._mgrs:
            m.start()

    async def wait_connected(self, timeout: float = 10.0) -> None:
        await asyncio.gather(*(m.wait_connected(timeout) for m in self._mgrs))

    async def stop(self) -> None:
        await asyncio.gather(*(m.stop() for m in self._mgrs))

    # --- DispatcherClusterBase ----------------------------------------------

    def select(self, idx: int):
        proxy = self._mgrs[idx].proxy
        return proxy if proxy is not None else _NULL_SENDER

    def count(self) -> int:
        return len(self._mgrs)

    def flush_all(self) -> None:
        for m in self._mgrs:
            if m.proxy is not None:
                m.proxy.flush()

"""Real TCP cluster client: one managed connection per dispatcher.

Reference parity: ``engine/dispatchercluster/dispatcherclient/DispatcherConnMgr.go``
— each game/gate process keeps one auto-reconnecting connection per
dispatcher; on (re)connect it re-sends the handshake (SET_GAME_ID carrying
the live entity list, or SET_GATE_ID), then pumps received packets into the
process's logic queue via the delegate (:66-88,123-147).

Resilience deviations from the reference (PR 3 — the reference drops
packets to dead dispatchers and reconnects on a fixed 1 s interval):

- While a link is down, sends land in a **byte-capped replay ring**
  (``[cluster] down_buffer_bytes``; drop-OLDEST on overflow, counted on
  ``cluster_dropped_packets_total{reason}``) and are replayed on the wire
  right after the reconnect handshake — per-link FIFO order is preserved,
  so a dispatcher restart is lossless up to the byte cap.
- Reconnects back off exponentially with **full jitter** (base
  ``RECONNECT_INTERVAL``, capped at ``[cluster] reconnect_max_interval``)
  instead of hammering a dead address at 1 Hz from every process at once.
- A **liveness watchdog** sends a HEARTBEAT msgtype on idle links (every
  ``peer_heartbeat_timeout / 3``) and hard-aborts a link silent past
  ``[cluster] peer_heartbeat_timeout`` — a half-open TCP connection (peer
  paused, NAT dropped, cable pulled) converts into the reconnect path
  instead of stalling until the OS gives up.
"""

from __future__ import annotations

import asyncio
import collections
import os
import random
import tempfile
import time
from typing import Callable, Deque, Optional, Sequence, Union

from goworld_tpu import consts, telemetry
from goworld_tpu.dispatchercluster import DispatcherClusterBase
from goworld_tpu.netutil.packet import Packet
from goworld_tpu.netutil.packet_conn import ConnectionClosed, PacketConnection
from goworld_tpu.proto.conn import GoWorldConnection
from goworld_tpu.proto.msgtypes import MsgType
from goworld_tpu.utils import gwlog

# A dispatcher endpoint: (host, port) for TCP, or a Unix-domain socket
# path for the co-located uds transport ([cluster] transport = uds) —
# same framing, handshakes, heartbeats, and replay rings either way.
DispatcherAddr = Union[tuple, str]


def uds_path_for(port: int, uds_dir: str = "") -> str:
    """The Unix-socket path a dispatcher with TCP port ``port`` serves
    beside its TCP listener when the uds transport is on. Derived from the
    port (unique per dispatcher by config validation) so games/gates need
    no extra per-dispatcher path configuration; ``uds_dir`` defaults to
    the system temp dir (keep it SHORT — sun_path caps at ~108 bytes)."""
    return os.path.join(
        uds_dir or tempfile.gettempdir(), f"gwt-disp-{port}.sock")


def dispatcher_addrs(cfg) -> list[DispatcherAddr]:
    """The dispatcher endpoints a game/gate should dial, honoring
    [cluster] transport: (host, port) tuples for tcp, socket paths for
    uds (single-host deploys where every process is co-located — the
    topology every bench and the chaos harness actually run)."""
    addrs = [cfg.dispatchers[i].addr for i in sorted(cfg.dispatchers)]
    c = getattr(cfg, "cluster", None)
    if c is not None and getattr(c, "transport", "tcp") == "uds":
        return [uds_path_for(port, c.uds_dir) for _, port in addrs]
    return addrs


# Delegate signature: (dispatcher_index, msgtype, packet) — must be fast/non-blocking.
PacketHandler = Callable[[int, int, Packet], None]
# Handshake factory: given the fresh GoWorldConnection, performs the hello.
# Receives (dispatcher_index, proxy): the game handshake must send each
# dispatcher ONLY the entity ids it owns by hash (the reference's
# GetEntityIDsForDispatcher, DispatcherConnMgr.go:79) — a full list creates
# stale entries on non-owner dispatchers that later REJECT the entity at a
# restore after it migrated (its REAL_MIGRATE only updated the owner).
Handshaker = Callable[[int, GoWorldConnection], None]

# Process-wide counters (one series per reason, not per link — links are
# few but long-lived metrics hygiene matches net_packets_total): "overflow"
# = ring evicted its oldest packet at the byte cap, "oversize" = a single
# packet larger than the whole cap, "disabled" = down_buffer_bytes is 0
# (legacy drop-on-down), "stopped" = packets still buffered when the
# process shut the link down for good.
_DROPPED = telemetry.counter(
    "cluster_dropped_packets_total",
    "Packets to a down dispatcher dropped instead of buffered/replayed.",
    ("reason",))
_REPLAYED = telemetry.counter(
    "cluster_replayed_packets_total",
    "Buffered packets replayed onto a reconnected dispatcher link.")
_RECONNECTS = telemetry.counter(
    "cluster_reconnects_total",
    "Completed dispatcher-link reconnect handshakes (beyond the first).")
_HB_KILLS = telemetry.counter(
    "cluster_link_heartbeat_kills_total",
    "Dispatcher links aborted for silence past peer_heartbeat_timeout.")


class _ReplayRing:
    """Byte-capped FIFO of (msgtype, payload) awaiting a reconnect.

    Drop-OLDEST on overflow: the freshest state (position syncs, latest
    attr changes) survives, and the ring can never stall a reconnect — a
    flush is at most ``cap`` bytes."""

    __slots__ = ("cap", "nbytes", "_buf")

    def __init__(self, cap: int) -> None:
        self.cap = cap
        self.nbytes = 0
        self._buf: Deque[tuple[int, bytes]] = collections.deque()

    def __len__(self) -> int:
        return len(self._buf)

    def push(self, msgtype: int, payload: bytes) -> None:
        if self.cap <= 0:
            _DROPPED.labels("disabled").inc()
            return
        if len(payload) > self.cap:
            _DROPPED.labels("oversize").inc()
            return
        self._buf.append((msgtype, payload))
        self.nbytes += len(payload)
        while self.nbytes > self.cap:
            _, old = self._buf.popleft()
            self.nbytes -= len(old)
            _DROPPED.labels("overflow").inc()

    def drain(self) -> Deque[tuple[int, bytes]]:
        buf = self._buf
        self._buf = collections.deque()
        self.nbytes = 0
        return buf


class _RingConn:
    """PacketConnection stand-in that captures typed sends into the ring.

    Wrapping it in a GoWorldConnection gives the full send_* surface for
    free, so the buffering sender stays layout-identical to a live link
    (the wire counters in proto/conn.py count a packet exactly once, at
    buffer time — the replay writes at the PacketConnection layer)."""

    closed = False

    def __init__(self, ring: _ReplayRing) -> None:
        self._ring = ring

    def send_packet(self, msgtype: int, packet: Packet) -> None:
        self._ring.push(msgtype, packet.payload)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class DispatcherConnMgr:
    """Managed connection to one dispatcher with auto-reconnect."""

    def __init__(
        self,
        index: int,
        addr: DispatcherAddr,
        handshake: Handshaker,
        on_packet: PacketHandler,
        on_disconnect: Optional[Callable[[int], None]] = None,
        *,
        down_buffer_bytes: Optional[int] = None,
        peer_heartbeat_timeout: Optional[float] = None,
        wait_connected_timeout: Optional[float] = None,
        reconnect_max_interval: Optional[float] = None,
    ) -> None:
        self.index = index
        self.addr = addr
        self._handshake = handshake
        self._on_packet = on_packet
        self._on_disconnect = on_disconnect
        self.down_buffer_bytes = (
            consts.CLUSTER_DOWN_BUFFER_BYTES
            if down_buffer_bytes is None else down_buffer_bytes)
        self.peer_heartbeat_timeout = (
            consts.CLUSTER_PEER_HEARTBEAT_TIMEOUT
            if peer_heartbeat_timeout is None else peer_heartbeat_timeout)
        self.wait_connected_timeout = (
            consts.CLUSTER_WAIT_CONNECTED_TIMEOUT
            if wait_connected_timeout is None else wait_connected_timeout)
        self.reconnect_max_interval = (
            consts.RECONNECT_INTERVAL_MAX
            if reconnect_max_interval is None else reconnect_max_interval)
        self.proxy: Optional[GoWorldConnection] = None
        self.ring = _ReplayRing(self.down_buffer_bytes)
        # trace_wire also on the buffering sender: a sampled packet parked
        # in the ring keeps its trailer and replays with the SAME trace id
        # after the reconnect — the outage shows as dispatcher queue-dwell
        # in the merged timeline, not as a lost trace.
        self._buffer_sender = GoWorldConnection(
            _RingConn(self.ring), trace_wire=True)
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        self._connected_event = asyncio.Event()
        self._connect_count = 0
        self._last_recv = 0.0

    @property
    def sender(self) -> GoWorldConnection:
        """The live link, or the ring-backed buffering sender while down."""
        proxy = self.proxy
        return proxy if proxy is not None else self._buffer_sender

    def _addr_str(self) -> str:
        addr = self.addr
        return addr if isinstance(addr, str) else f"{addr[0]}:{addr[1]}"

    async def _open(self):
        """Dial the dispatcher over whichever transport the address names
        (uds paths and tcp tuples yield the same stream pair — everything
        above this call is transport-blind)."""
        if isinstance(self.addr, str):
            return await asyncio.open_unix_connection(self.addr)
        return await asyncio.open_connection(*self.addr)

    def link_state(self) -> dict:
        """One JSON-able row for /healthz: link up?, last-seen age,
        packets parked in the replay ring."""
        up = self.proxy is not None
        return {
            "index": self.index,
            "addr": self._addr_str(),
            "connected": up,
            "last_seen_age_s": (
                round(time.monotonic() - self._last_recv, 3)
                if self._last_recv else None),
            "buffered_packets": len(self.ring),
            "connects": self._connect_count,
        }

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def wait_connected(self, timeout: Optional[float] = None) -> None:
        t = self.wait_connected_timeout if timeout is None else timeout
        try:
            await asyncio.wait_for(self._connected_event.wait(), t)
        except asyncio.TimeoutError:
            raise TimeoutError(
                f"dispatcher {self.index} at {self._addr_str()} "
                f"not connected after {t:.1f}s (reconnect keeps retrying in "
                f"the background)"
            ) from None

    def _backoff_delay(self, attempt: int) -> float:
        """Exponential backoff with FULL jitter: uniform(0, min(cap,
        base * 2^attempt)) — spreads the post-restart thundering herd of
        every game/gate reconnecting at once."""
        ceiling = min(
            self.reconnect_max_interval,
            consts.RECONNECT_INTERVAL * (2.0 ** min(attempt, 16)),
        )
        return random.uniform(0, ceiling)

    def _flush_ring(self, proxy: GoWorldConnection) -> None:
        """Replay buffered sends right after the reconnect handshake, in
        FIFO order, at the PacketConnection layer (already counted on the
        wire totals when they entered the ring)."""
        buf = self.ring.drain()
        if not buf:
            return
        n, nbytes = len(buf), 0
        for msgtype, payload in buf:
            nbytes += len(payload)
            proxy.conn.send_packet(msgtype, Packet(payload))
        _REPLAYED.inc(n)
        gwlog.infof(
            "dispatcher conn %d: replayed %d buffered packets (%d bytes) "
            "after reconnect", self.index, n, nbytes)

    async def _heartbeat_loop(self, proxy: GoWorldConnection) -> None:
        """Send HEARTBEAT on an idle link; abort a link silent past the
        deadline so the recv pump converts it into the reconnect path."""
        timeout = self.peer_heartbeat_timeout
        interval = max(0.05, timeout / 3.0)
        mark = proxy.conn.sent_packets
        while True:
            await asyncio.sleep(interval)
            now = time.monotonic()
            if now - self._last_recv > timeout:
                gwlog.warnf(
                    "dispatcher conn %d: peer silent for %.1fs "
                    "(> %.1fs heartbeat deadline); aborting half-open link",
                    self.index, now - self._last_recv, timeout)
                _HB_KILLS.inc()
                proxy.conn.abort()
                return
            if proxy.conn.sent_packets == mark:
                proxy.send_cluster_heartbeat()
            mark = proxy.conn.sent_packets

    async def _run(self) -> None:
        """Connect → handshake → ring replay → recv pump; repeat forever
        with jittered backoff (DispatcherConnMgr.go:66-147)."""
        attempt = 0
        while not self._stopped:
            try:
                reader, writer = await self._open()
            except OSError:
                await asyncio.sleep(self._backoff_delay(attempt))
                attempt += 1
                continue
            proxy = GoWorldConnection(
                PacketConnection(reader, writer), trace_wire=True)
            hb_task: Optional[asyncio.Task] = None
            try:
                self._handshake(self.index, proxy)
                # Publish the live proxy only after the handshake is queued
                # and the ring is replayed behind it, so no concurrent send
                # can overtake either.
                self._flush_ring(proxy)
                self.proxy = proxy
                self._connected_event.set()
                attempt = 0
                self._connect_count += 1
                if self._connect_count > 1:
                    _RECONNECTS.inc()
                self._last_recv = time.monotonic()
                if self.peer_heartbeat_timeout > 0:
                    hb_task = asyncio.get_running_loop().create_task(
                        self._heartbeat_loop(proxy))
                while True:
                    msgtype, packet = await proxy.recv()
                    self._last_recv = time.monotonic()
                    if msgtype == MsgType.HEARTBEAT:
                        continue  # liveness only; never routed to logic
                    self._on_packet(self.index, msgtype, packet)
            except ConnectionClosed:
                pass
            except Exception:
                gwlog.trace_error("dispatcher conn %d: recv pump error", self.index)
            finally:
                self.proxy = None
                self._connected_event.clear()
                if hb_task is not None:
                    hb_task.cancel()
                    try:
                        await hb_task
                    except (asyncio.CancelledError, Exception):
                        pass
                proxy.close()
                if self._on_disconnect is not None and not self._stopped:
                    self._on_disconnect(self.index)
            if not self._stopped:
                gwlog.warnf(
                    "dispatcher conn %d lost; reconnecting (sends buffer up "
                    "to %d bytes)", self.index, self.down_buffer_bytes)
                await asyncio.sleep(self._backoff_delay(attempt))
                attempt += 1

    async def stop(self) -> None:
        self._stopped = True
        if self.proxy is not None:
            # Drain before close: the process exits right after stop() during
            # freeze/terminate, and packets still in the asyncio transport
            # buffer would be silently dropped — including REAL_MIGRATE of an
            # avatar that just migrated out, which then exists on NO game.
            try:
                await asyncio.wait_for(
                    self.proxy.conn.drain(hard=True), timeout=5.0
                )
            except Exception:
                pass  # peer already gone; nothing to preserve
            self.proxy.close()
        if len(self.ring):
            # Buffered sends die with the process — visible, not silent.
            _DROPPED.labels("stopped").inc(len(self.ring))
            self.ring.drain()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass


def cluster_knobs(cfg) -> dict:
    """ClusterClient kwargs from a GoWorldConfig's [cluster] section."""
    c = getattr(cfg, "cluster", None)
    if c is None:
        return {}
    return dict(
        down_buffer_bytes=c.down_buffer_bytes,
        peer_heartbeat_timeout=c.peer_heartbeat_timeout,
        wait_connected_timeout=c.wait_connected_timeout,
        reconnect_max_interval=c.reconnect_max_interval,
    )


class ClusterClient(DispatcherClusterBase):
    """The process-wide dispatcher fabric client (dispatchercluster.go:18-37)."""

    def __init__(
        self,
        addrs: Sequence[DispatcherAddr],
        handshake: Handshaker,
        on_packet: PacketHandler,
        on_disconnect: Optional[Callable[[int], None]] = None,
        **knobs,
    ) -> None:
        self._mgrs = [
            DispatcherConnMgr(i, addr, handshake, on_packet, on_disconnect,
                              **knobs)
            for i, addr in enumerate(addrs)
        ]

    def start(self) -> None:
        for m in self._mgrs:
            m.start()

    async def wait_connected(self, timeout: Optional[float] = None) -> None:
        await asyncio.gather(*(m.wait_connected(timeout) for m in self._mgrs))

    async def stop(self) -> None:
        await asyncio.gather(*(m.stop() for m in self._mgrs))

    # --- DispatcherClusterBase ----------------------------------------------

    def select(self, idx: int):
        """The live link for dispatcher ``idx``, or its ring-buffering
        sender while the link is down (drop-on-down is gone: sends survive
        a dispatcher restart up to the ring's byte cap)."""
        return self._mgrs[idx].sender

    def count(self) -> int:
        return len(self._mgrs)

    def link_states(self) -> list[dict]:
        """Per-dispatcher link health rows (GET /healthz)."""
        return [m.link_state() for m in self._mgrs]

    def flush_all(self) -> None:
        for m in self._mgrs:
            if m.proxy is not None:
                m.proxy.flush()

"""Client side of the dispatcher fabric.

Reference parity: ``engine/dispatchercluster`` — every game/gate process keeps
one connection per dispatcher, selects a dispatcher per entity by id-hash
(``hashEntityID % N``, hash.go:7-12 → per-entity FIFO ordering), and fans out
broadcast sends to all dispatchers (dispatchercluster.go:18-137).

Until ``initialize`` runs, all sends are silently dropped — this mirrors the
reference where entity unit tests run without a dispatcher and senders no-op
(SURVEY.md §4.1).
"""

from __future__ import annotations

from typing import Callable, Optional

from goworld_tpu.common import hash_entity_id

_cluster: Optional["DispatcherClusterBase"] = None


class DispatcherClusterBase:
    """Interface of the cluster client (real impl: cluster.ClusterClient)."""

    def select(self, idx: int):  # → GoWorldConnection-like sender
        raise NotImplementedError

    def count(self) -> int:
        raise NotImplementedError

    def flush_all(self) -> None:
        pass


class _NullSender:
    """Swallows every send_* call (disconnected / test mode)."""

    def __getattr__(self, name: str) -> Callable:
        if name.startswith("send_"):
            return lambda *a, **kw: None
        raise AttributeError(name)


_NULL_SENDER = _NullSender()


def set_cluster(cluster: Optional[DispatcherClusterBase]) -> None:
    global _cluster
    _cluster = cluster


def get_cluster() -> Optional[DispatcherClusterBase]:  # gwlint: keep — accessor beside set_cluster/is_connected
    return _cluster


def is_connected() -> bool:
    return _cluster is not None


def select_by_entity_id(eid: str):
    """Route by entity id → the same dispatcher always sees the same entity
    (dispatchercluster.go:116-119)."""
    if _cluster is None:
        return _NULL_SENDER
    return _cluster.select(hash_entity_id(eid) % _cluster.count())


def select_by_gate_id(gateid: int):
    if _cluster is None:
        return _NULL_SENDER
    return _cluster.select(gateid % _cluster.count())


def select_by_srv_id(srvid: str):
    from goworld_tpu.common import hash_string

    if _cluster is None:
        return _NULL_SENDER
    return _cluster.select(hash_string(srvid) % _cluster.count())


def select_all():
    """All dispatcher connections (broadcast fan-out)."""
    if _cluster is None:
        return []
    return [_cluster.select(i) for i in range(_cluster.count())]

"""``python -m goworld_tpu.gate`` — gate process binary."""

import sys

from goworld_tpu.gate import run

sys.exit(run())

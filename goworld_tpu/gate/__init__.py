"""Gate process: client socket ownership + protocol fan-in.

Reference parity: ``components/gate`` — the gate owns client connections,
assigns ClientIDs, generates boot-entity IDs, forwards client RPCs into the
dispatcher fabric and pushes entity/attr/position updates back out to clients
(gate.go:57-101, GateService.go).
"""

from goworld_tpu.gate.filter_tree import FilterTree
from goworld_tpu.gate.service import GateService, run

__all__ = ["FilterTree", "GateService", "run"]

"""GateService: client sockets, boot flow, filter broadcast, sync batching.

Reference parity: ``components/gate/GateService.go`` —

- One recv task per client connection feeding a single logic loop (no locks
  in logic, :427-448).
- The gate (not the game) generates the boot EntityID and announces the fresh
  client to a dispatcher selected by that id (:213-218).
- Client→server position syncs are coalesced per dispatcher and flushed every
  ``position_sync_interval`` (:398-425); server→client syncs arrive batched
  per gate and are de-multiplexed per clientid (:346-371).
- Redirect-range packets (game→client) carry a [u16 gateid][clientid] prefix
  which the gate strips before forwarding; is-player CREATE_ENTITY_ON_CLIENT
  packets are sniffed to track each proxy's owner entity (:262-293).
- Filter-prop trees per key serve CALL_FILTERED_CLIENTS with 6 comparison
  ops (FilterTree.go:12-102).
- Heartbeat timeouts kill client proxies (:201-211), counted on
  ``gate_clients_killed_total{reason}`` with ONE aggregated warn per sweep.
  Deviation: the reference gate exits when a dispatcher connection dies
  (gate.go:138-143); this gate rides out dispatcher restarts — sends
  buffer in the per-link replay ring and flush after reconnect.

Transports: TCP (+ optional TLS via asyncio ssl, mirroring the reference's
crypto/tls wrap, gate.go:97-118), reliable UDP on the same port number
(the reference's KCP slot, GateService.go:134-165 — in-repo ARQ protocol,
netutil/rudp.py), WebSocket when ``ws_addr`` is set (gate.go:92-95;
netutil/ws_conn.py), and optional per-packet zlib compression (the
reference uses snappy, ClientProxy.go:42-45 — snappy is not in this
image).
"""

from __future__ import annotations

import asyncio
import ssl
import time
from typing import Optional

import numpy as np

from goworld_tpu import consts
from goworld_tpu.common import gen_client_id, gen_entity_id, hash_entity_id
from goworld_tpu.config import GateConfig, GoWorldConfig
from goworld_tpu.dispatchercluster.cluster import ClusterClient
from goworld_tpu.gate.filter_tree import FilterTree
from goworld_tpu.netutil.packet import Packet
from goworld_tpu.netutil.packet_conn import ConnectionClosed, PacketConnection
from goworld_tpu.proto.conn import (
    CLIENT_DELTA_SYNC_DTYPE,
    CLIENT_SYNC_DTYPE,
    DELTA_SYNC_RECORD_SIZE,
    SYNC_RECORD_SIZE,
    GoWorldConnection,
)
from goworld_tpu.proto.msgtypes import FilterOp, MsgType, is_gate_redirect
from goworld_tpu.telemetry import tracing
from goworld_tpu.utils import gwlog, opmon

_CLIENT_BLOCK_SIZE = 16 + SYNC_RECORD_SIZE  # clientid + sync record
_CLIENT_DELTA_BLOCK_SIZE = 16 + DELTA_SYNC_RECORD_SIZE  # cid + delta record

# Client proxies killed by the gate itself (vs. orderly client disconnects):
# reason="heartbeat" = silent past [gateN] heartbeat_timeout (swept in
# batches — the sweep logs ONE aggregated warn, so a mass timeout after a
# network partition cannot flood the log), reason="error" = the recv pump
# died on a non-clean error. Process-wide series, same churn reasoning as
# net_packets_total.
from goworld_tpu import telemetry as _telemetry

_CLIENT_KILLS = _telemetry.counter(
    "gate_clients_killed_total",
    "Client proxies killed by the gate, by reason.", ("reason",))
_KILLS_HEARTBEAT = _CLIENT_KILLS.labels("heartbeat")
_KILLS_ERROR = _CLIENT_KILLS.labels("error")

# Sync fan-out per-hop attribution (shared family with game_pack and
# dispatcher_route; bench.py --fanout reads the deltas into shares):
# gate_demux = the argsort demux of one sync packet, client_write = the
# end-of-batch uncork sweep that actually writes the corked client conns.
_HOP_SECONDS = _telemetry.counter(
    "fanout_hop_seconds_total",
    "Busy wall seconds per sync fan-out hop (game_collect|game_pack|"
    "game_send|dispatcher_route|gate_demux|client_write).",
    ("hop",))
_HOP_GATE_DEMUX = _HOP_SECONDS.labels("gate_demux")
_HOP_CLIENT_WRITE = _HOP_SECONDS.labels("client_write")


class ClientProxy:
    """Server-side handle of one connected client (ClientProxy.go:39-52)."""

    __slots__ = ("clientid", "conn", "owner_eid", "heartbeat_time",
                 "filter_props", "_gate")

    def __init__(self, conn: GoWorldConnection, gate=None) -> None:
        self.clientid = gen_client_id()
        self.conn = conn
        self.owner_eid: str = ""
        self.heartbeat_time = time.monotonic()
        self.filter_props: dict[str, str] = {}
        self._gate = gate  # owning GateService (None for bare-proxy tests)

    def send(self, msgtype: int, payload: bytes) -> None:
        # Tick-scoped write coalescing: while the gate logic loop is inside
        # an event batch, the first write corks the connection (buffer, no
        # flush task) and registers it for the end-of-batch uncork — N
        # packets to one client leave in ONE transport write per tick.
        gate = self._gate
        if gate is not None and gate._batch_active:
            conn = self.conn
            if conn not in gate._corked_conns:
                conn.cork()
                gate._corked_conns.add(conn)
        self.conn.send_packet_raw(msgtype, payload)

    def close(self) -> None:
        self.conn.close()

    def __repr__(self) -> str:
        return f"ClientProxy<{self.clientid}|owner={self.owner_eid or '-'}>"


class GateService:
    """One gate process. Construct, then ``await service.run_async()``."""

    def __init__(self, gateid: int, cfg: Optional[GoWorldConfig] = None) -> None:
        from goworld_tpu.config import get as get_config

        self.gateid = gateid
        self.cfg = cfg or get_config()
        self.gate_cfg: GateConfig = self.cfg.gates.get(gateid) or GateConfig()
        self.clients: dict[str, ClientProxy] = {}
        self.filter_trees: dict[str, FilterTree] = {}
        # Dispatcher indices this instance has handshaked at least once
        # (the "fresh process" bit of SET_GATE_ID derives from it).
        self._handshaked: set[int] = set()
        # Boot generation of this gate process (non-zero): clients carry
        # it on NOTIFY_CLIENT_CONNECTED; a restart's stale-client detach
        # broadcast names it as the valid generation (game_client.py).
        import random as _random

        self.generation: int = _random.getrandbits(32) | 1
        self.cluster: Optional[ClusterClient] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._queue: asyncio.Queue = asyncio.Queue()
        self._tasks: list[asyncio.Task] = []
        self._stopped = asyncio.Event()
        # client→server sync coalescing: dispatcher index → 32 B records;
        # a buffer reaching [cluster] sync_flush_bytes flushes immediately
        # instead of waiting out position_sync_interval (0 = tick only).
        self._pending_syncs: dict[int, bytearray] = {}
        ccfg = getattr(self.cfg, "cluster", None)
        self._sync_flush_bytes = (
            ccfg.sync_flush_bytes if ccfg is not None
            else consts.DISPATCHER_SYNC_FLUSH_BYTES)
        # server→client write coalescing (tick-scoped): True while the
        # logic loop is inside one event batch; conns corked this batch.
        self._batch_active = False
        self._corked_conns: set = set()
        self.port: int = 0
        self._ws_server = None
        self._rudp_listener = None
        self.ws_port: int = 0
        self._debug_srv = None
        self.exit_code: Optional[int] = None

    # --- lifecycle (gate.go:57-101) ----------------------------------------

    async def run_async(self) -> int:
        await self.start()
        await self._stopped.wait()
        await self.stop()
        return self.exit_code or 0

    async def start(self) -> None:
        self._started_at = time.monotonic()
        tcfg = getattr(self.cfg, "telemetry", None)
        if tcfg is not None:
            tracing.configure_from_config(tcfg)
        from goworld_tpu.dispatchercluster.cluster import (
            cluster_knobs,
            dispatcher_addrs,
        )

        self.cluster = ClusterClient(
            dispatcher_addrs(self.cfg), self._handshake,
            self._on_dispatcher_packet,
            self._on_dispatcher_disconnect, **cluster_knobs(self.cfg)
        )
        self.cluster.start()

        ssl_ctx = self._make_ssl_context()
        self._server = await asyncio.start_server(
            self._serve_client, self.gate_cfg.host, self.gate_cfg.port, ssl=ssl_ctx
        )
        self.port = self._server.sockets[0].getsockname()[1]
        await self._start_rudp_server()
        await self._start_ws_server(ssl_ctx)
        from goworld_tpu.utils import gwvar
        from goworld_tpu.utils.debug_http import setup_http_server

        gwvar.set_var("NumClients", lambda: len(self.clients))
        self._register_metrics()
        from goworld_tpu.utils import debug_http

        debug_http.set_health_provider(self._health)
        self._debug_srv = await setup_http_server(self.gate_cfg.http_addr)
        loop = asyncio.get_running_loop()
        if tcfg is not None and getattr(tcfg, "history_dir", ""):
            # Black-box history ring (telemetry/history.py) — the gate
            # has no flight recorder, so frames carry health + metric
            # deltas only.
            import os as _os

            from goworld_tpu.telemetry import history as history_mod

            self._hist_writer = history_mod.HistoryWriter(
                _os.path.join(tcfg.history_dir, f"gate{self.gateid}"),
                f"gate{self.gateid}",
                interval=tcfg.history_interval,
                segment_bytes=tcfg.history_segment_bytes,
                segments=tcfg.history_segments,
                health=self._health)
            history_mod.set_active_writer(self._hist_writer)
            self._tasks.append(loop.create_task(self._hist_writer.run()))
        self._tasks.append(loop.create_task(self._logic_loop()))
        self._tasks.append(loop.create_task(self._tick_loop()))
        gwlog.infof("gate %d listening on %s:%d (tls=%s)",
                    self.gateid, self.gate_cfg.host, self.port, ssl_ctx is not None)
        gwlog.infof(consts.GATE_STARTED_TAG)

    def _register_metrics(self) -> None:
        """Queue-depth / client-count gauges on /metrics, labeled by
        gateid (pull-sampled — zero logic-loop cost). Per-packet in/out
        volume is counted transport-uniformly in proto/conn.py
        (net_*_total), which covers TCP, WS, and KCP client conns alike."""
        from goworld_tpu import telemetry

        g = str(self.gateid)
        telemetry.gauge(
            "gate_queue_depth",
            "Events waiting in the gate logic queue.", ("gateid",),
        ).labels(g).set_function(self._queue.qsize)
        telemetry.gauge(
            "gate_clients", "Connected client proxies.", ("gateid",),
        ).labels(g).set_function(lambda: len(self.clients))

    def _unregister_metrics(self) -> None:
        from goworld_tpu import telemetry

        g = str(self.gateid)
        for name in ("gate_queue_depth", "gate_clients"):
            fam = telemetry.family(name)
            if fam is not None:
                fam.remove(g)

    def _health(self) -> dict:
        """One JSON object for GET /healthz (and the /snapshot row the
        cluster collector aggregates — ``generation`` is the value every
        game binding and dispatcher registration must carry for this
        gate, or the /cluster summary flags a stale generation row)."""
        return {
            "kind": "gate",
            "id": self.gateid,
            "uptime_s": round(
                time.monotonic() - getattr(self, "_started_at", 0.0), 3),
            "generation": self.generation,
            "clients": len(self.clients),
            "queue_depth": self._queue.qsize(),
            "dispatcher_links": (
                self.cluster.link_states() if self.cluster is not None
                else []),
        }

    async def stop(self) -> None:
        from goworld_tpu.utils import debug_http

        debug_http.clear_health_provider(self._health)
        self._unregister_metrics()
        hist_writer = getattr(self, "_hist_writer", None)
        if hist_writer is not None:
            from goworld_tpu.telemetry import history as history_mod

            hist_writer.close()
            history_mod.clear_active_writer(hist_writer)
            self._hist_writer = None
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()
        if self._server is not None:
            self._server.close()
            # Close live client sockets BEFORE wait_closed(): since 3.12.1
            # it waits for connection handlers, which only exit once their
            # sockets close (same fix as DispatcherService.stop).
            for cp in list(self.clients.values()):
                cp.close()
            await self._server.wait_closed()
        if self._ws_server is not None:
            self._ws_server.close()
            await self._ws_server.wait_closed()
        if self._rudp_listener is not None:
            self._rudp_listener.close()
            self._rudp_listener = None
        if getattr(self, "_debug_srv", None) is not None:
            await self._debug_srv.stop()
            self._debug_srv = None
        from goworld_tpu.utils import gwvar

        gwvar.unset("NumClients")
        for cp in list(self.clients.values()):
            cp.close()
        self.clients.clear()
        if self.cluster is not None:
            await self.cluster.stop()

    def terminate(self) -> None:
        self.exit_code = 0
        self._stopped.set()

    def _make_ssl_context(self) -> Optional[ssl.SSLContext]:
        if not self.gate_cfg.encrypt_connection:
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.gate_cfg.rsa_cert, self.gate_cfg.rsa_key)
        return ctx

    def _handshake(self, index: int, proxy: GoWorldConnection) -> None:
        # fresh = first contact between THIS gate process and dispatcher
        # ``index``: a brand-new gate introduces itself so the dispatcher
        # detaches the dead predecessor's client bindings on every game
        # (crash + restart inside the reconnect-grace window); a surviving
        # gate re-dialing after a link blip keeps its live clients.
        fresh = index not in self._handshaked
        self._handshaked.add(index)
        proxy.send_set_gate_id(self.gateid, fresh=fresh,
                               gen=self.generation)

    def _on_dispatcher_disconnect(self, index: int) -> None:
        # Deliberate deviation from the reference, which EXITS the whole
        # gate (dropping every connected client) when one dispatcher link
        # dies (gate.go:138-143). With the replay ring + reconnect loop
        # (dispatchercluster/cluster.py) the gate now rides out dispatcher
        # restarts: sends buffer up to [cluster] down_buffer_bytes and
        # replay after the reconnect handshake, and clients never notice.
        gwlog.warnf("gate %d: dispatcher %d disconnected; buffering sends "
                    "until reconnect", self.gateid, index)

    # --- client connections (GateService.go:125-199) ------------------------

    async def _serve_client(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        pconn = PacketConnection(reader, writer)
        if self.gate_cfg.compress_connection:
            pconn.enable_compression(self.gate_cfg.compress_format)
        await self._pump_client(GoWorldConnection(pconn))

    async def _start_rudp_server(self) -> None:
        """Serve the reliable-UDP transport on the SAME port number as TCP
        (the reference serves KCP beside TCP on one address,
        GateService.go:134-165). [gate] rudp_protocol picks the wire
        protocol: "kcp" = the real KCP segment protocol (netutil/kcp.py,
        stock-KCP interoperable) or "native" = the in-repo ARQ
        (netutil/rudp.py)."""
        loop = asyncio.get_running_loop()

        def accept(pconn) -> None:
            if self.gate_cfg.compress_connection:
                pconn.enable_compression(self.gate_cfg.compress_format)
            loop.create_task(self._pump_client(GoWorldConnection(pconn)))

        if self.gate_cfg.rudp_protocol == "kcp":
            from goworld_tpu.config.read_config import parse_fec
            from goworld_tpu.netutil.kcp import KCPListener

            self._rudp_listener = KCPListener(
                accept, fec=parse_fec(self.gate_cfg.rudp_fec))
        else:
            from goworld_tpu.netutil.rudp import RUDPListener

            self._rudp_listener = RUDPListener(accept)
        try:
            await loop.create_datagram_endpoint(
                lambda: self._rudp_listener,
                local_addr=(self.gate_cfg.host, self.port),
            )
        except OSError as exc:
            # UDP port taken is non-fatal: TCP/WS clients still work.
            gwlog.errorf("gate %d: rudp listener failed: %s", self.gateid, exc)
            self._rudp_listener = None
            return
        gwlog.infof("gate %d rudp (reliable udp) listening on %s:%d",
                    self.gateid, self.gate_cfg.host, self.port)

    async def _start_ws_server(self, ssl_ctx) -> None:
        """Serve WebSocket clients next to TCP when [gateN] ws_addr is set
        (gate.go:92-95; transport adapter in netutil/ws_conn.py)."""
        if not self.gate_cfg.ws_addr:
            return
        import websockets

        from goworld_tpu.netutil.ws_conn import WSPacketConnection

        host, _, port = self.gate_cfg.ws_addr.rpartition(":")

        async def handler(ws):
            await self._pump_client(GoWorldConnection(WSPacketConnection(ws)))

        self._ws_server = await websockets.serve(
            handler, host or "127.0.0.1", int(port), ssl=ssl_ctx, max_size=consts.MAX_PACKET_SIZE
        )
        self.ws_port = self._ws_server.sockets[0].getsockname()[1]
        gwlog.infof("gate %d websocket listening on %s:%d", self.gateid,
                    host or "127.0.0.1", self.ws_port)

    async def _pump_client(self, conn: GoWorldConnection) -> None:
        """Per-connection recv pump → single logic queue (any transport)."""
        cp = ClientProxy(conn, self)
        self._queue.put_nowait(("connect", cp, 0, None))
        try:
            while True:
                msgtype, packet = await conn.recv()
                self._queue.put_nowait(("packet", cp, msgtype, packet))
        except ConnectionClosed:
            pass
        except Exception:
            _KILLS_ERROR.inc()
            gwlog.trace_error("gate %d: client %s recv pump error; killing",
                              self.gateid, cp.clientid)
        finally:
            conn.close()
            self._queue.put_nowait(("disconnect", cp, 0, None))

    async def _logic_loop(self) -> None:
        while True:
            # Drain the whole burst without yielding (the game loop batches
            # its packet queue the same way), with client connections
            # corked for the span of the batch: a dispatcher sync packet
            # fanning out to hundreds of proxies costs each client ONE
            # transport write per batch instead of one per packet.
            batch = [await self._queue.get()]
            while True:
                try:
                    batch.append(self._queue.get_nowait())
                except asyncio.QueueEmpty:
                    break
            self._batch_active = True
            try:
                for kind, cp, msgtype, packet in batch:
                    try:
                        if kind == "packet":
                            # opmon wraps gate packet handling like the
                            # reference (GateService.go:431-438); slow ops
                            # warn at 100 ms.
                            op = opmon.Operation("gate.handleClientPacket")
                            self._handle_client_packet(cp, msgtype, packet)
                            op.finish(warn_threshold=0.1)
                        elif kind == "connect":
                            self._on_new_client(cp)
                        elif kind == "disconnect":
                            self._on_client_gone(cp)
                        elif kind == "dispatcher":
                            self._handle_dispatcher_packet(msgtype, packet)
                    except Exception:
                        gwlog.trace_error("gate %d: error handling %s/%s",
                                          self.gateid, kind, msgtype)
            finally:
                self._batch_active = False
                t0 = time.perf_counter()
                for conn in self._corked_conns:
                    try:
                        conn.uncork()
                    except Exception:  # a dead conn must not strand others
                        pass
                if self._corked_conns:
                    _HOP_CLIENT_WRITE.inc(time.perf_counter() - t0)
                self._corked_conns.clear()

    async def _tick_loop(self) -> None:
        last_flush = time.monotonic()
        while True:
            await asyncio.sleep(consts.GATE_SERVICE_TICK_INTERVAL)
            now = time.monotonic()
            if now - last_flush >= self.gate_cfg.position_sync_interval:
                last_flush = now
                self._flush_pending_syncs()
            self._sweep_heartbeats(now)

    def _select_by_eid(self, eid: str):
        """Entity-id-hash dispatcher selection over the gate's OWN cluster —
        never the process-global one, which belongs to the game side."""
        assert self.cluster is not None
        return self.cluster.select(hash_entity_id(eid) % self.cluster.count())

    def _on_new_client(self, cp: ClientProxy) -> None:
        """Register the proxy and announce it with a fresh boot-entity id
        (GateService.go:213-218)."""
        self.clients[cp.clientid] = cp
        boot_eid = gen_entity_id()
        self._select_by_eid(boot_eid).send_notify_client_connected(
            cp.clientid, self.gateid, boot_eid, gate_gen=self.generation
        )
        gwlog.debugf("gate %d: client %s connected, boot entity %s", self.gateid, cp.clientid, boot_eid)

    def _on_client_gone(self, cp: ClientProxy) -> None:
        if self.clients.pop(cp.clientid, None) is None:
            return  # already removed (heartbeat kill)
        self._clear_filter_props(cp)
        if cp.owner_eid:
            self._select_by_eid(cp.owner_eid).send_notify_client_disconnected(
                cp.clientid, cp.owner_eid
            )

    def _sweep_heartbeats(self, now: float) -> None:
        timeout = self.gate_cfg.heartbeat_timeout
        if timeout <= 0:
            return
        killed: list[str] = []
        for cp in list(self.clients.values()):
            if now - cp.heartbeat_time > timeout:
                killed.append(cp.clientid)
                cp.close()  # recv task will enqueue the disconnect
        if killed:
            _KILLS_HEARTBEAT.inc(len(killed))
            # One aggregated warn per sweep: a mass timeout (network
            # partition upstream of thousands of clients) must not emit
            # one log line per client.
            gwlog.warnf(
                "gate %d: killed %d client(s) past the %.0fs heartbeat "
                "timeout (e.g. %s)", self.gateid, len(killed), timeout,
                ", ".join(killed[:3]))

    # --- client → server (GateService.go:245-248,398-425) -------------------

    def _handle_client_packet(self, cp: ClientProxy, msgtype: int, packet: Packet) -> None:
        cp.heartbeat_time = time.monotonic()
        if msgtype == MsgType.HEARTBEAT_FROM_CLIENT:
            return
        if msgtype == MsgType.SYNC_POSITION_YAW_FROM_CLIENT:
            record = packet.payload[:SYNC_RECORD_SIZE]
            eid = record[:16].decode("ascii")
            idx = hash_entity_id(eid) % max(1, self.cluster.count() if self.cluster else 1)
            buf = self._pending_syncs.setdefault(idx, bytearray())
            buf += record
            if (self._sync_flush_bytes
                    and len(buf) >= self._sync_flush_bytes
                    and self.cluster is not None):
                # Size-triggered early flush: a burst never sits out the
                # rest of position_sync_interval.
                del self._pending_syncs[idx]
                self.cluster.select(idx).send_sync_position_yaw_from_client(
                    bytes(buf))
            return
        if msgtype == MsgType.CALL_ENTITY_METHOD_FROM_CLIENT:
            eid = packet.read_entity_id()
            # Ingress seam 1: a client RPC entering the cluster head-
            # samples a fresh root trace (1/[telemetry] trace_sample_rate).
            # The method name is parsed only on the sampled path.
            scope = tracing.root_scope("gate.client_rpc")
            if scope is not None:
                scope.args = {"eid": eid, "method": packet.read_varstr(),
                              "gateid": self.gateid}
            packet.set_read_pos(0)
            packet.append_client_id(cp.clientid)
            if scope is None:
                self._select_by_eid(eid).send(
                    MsgType.CALL_ENTITY_METHOD_FROM_CLIENT, packet)
            else:
                with scope:
                    self._select_by_eid(eid).send(
                        MsgType.CALL_ENTITY_METHOD_FROM_CLIENT, packet)
            return
        gwlog.warnf("gate %d: unexpected client msgtype %s", self.gateid, msgtype)

    def _flush_pending_syncs(self) -> None:
        if not self._pending_syncs or self.cluster is None:
            return
        for idx, buf in self._pending_syncs.items():
            self.cluster.select(idx).send_sync_position_yaw_from_client(bytes(buf))
        self._pending_syncs.clear()

    # --- dispatcher → gate ---------------------------------------------------

    def _on_dispatcher_packet(self, index: int, msgtype: int, packet: Packet) -> None:
        self._queue.put_nowait(("dispatcher", None, msgtype, packet))

    def _handle_dispatcher_packet(self, msgtype: int, packet: Packet) -> None:
        if packet.trace is not None:
            # Tail of a sampled trace: the client fan-out span (queue
            # dwell child + redirect strip + client write). Client links
            # carry no trailer, so the trace ends here by design.
            scope = tracing.continue_from_packet(
                packet, "gate.client_fanout", dwell_name="gate.queue_dwell")
            scope.args["msgtype"] = int(msgtype)
            scope.args["gateid"] = self.gateid
            with scope:
                self._dispatch_dispatcher_packet(msgtype, packet)
            return
        self._dispatch_dispatcher_packet(msgtype, packet)

    def _dispatch_dispatcher_packet(self, msgtype: int, packet: Packet) -> None:
        if is_gate_redirect(msgtype):
            self._handle_redirect(msgtype, packet)
        elif msgtype == MsgType.SYNC_POSITION_YAW_ON_CLIENTS:
            self._handle_sync_on_clients(packet)
        elif msgtype == MsgType.SYNC_POSITION_YAW_DELTA_ON_CLIENTS:
            self._handle_sync_delta_on_clients(packet)
        elif msgtype == MsgType.CALL_FILTERED_CLIENTS:
            self._handle_call_filtered_clients(packet)
        else:
            gwlog.warnf("gate %d: unhandled dispatcher msgtype %s", self.gateid, msgtype)

    def _handle_redirect(self, msgtype: int, packet: Packet) -> None:
        """Strip the [u16 gateid][clientid] prefix and forward to the client;
        sniff is-player creates for owner tracking (GateService.go:262-293)."""
        packet.read_uint16()  # gateid (it is ours; dispatcher routed on it)
        clientid = packet.read_client_id()
        cp = self.clients.get(clientid)
        if msgtype == MsgType.SET_CLIENTPROXY_FILTER_PROP:
            if cp is not None:
                self._set_filter_prop(cp, packet.read_varstr(), packet.read_varstr())
            return
        if msgtype == MsgType.CLEAR_CLIENTPROXY_FILTER_PROPS:
            if cp is not None:
                self._clear_filter_props(cp)
            return
        if cp is None:
            return  # client already gone; drop quietly (reference behavior)
        rest = packet.read_rest()
        if msgtype == MsgType.CREATE_ENTITY_ON_CLIENT:
            if len(rest) < 17:  # bool is_player + eid(16), proto/schema.py
                raise ValueError(
                    f"CREATE_ENTITY_ON_CLIENT payload truncated "
                    f"({len(rest)} bytes after the redirect prefix)")
            is_player = rest[0] != 0
            if is_player:
                cp.owner_eid = rest[1:17].decode("ascii")
        cp.send(msgtype, rest)

    def _handle_sync_on_clients(self, packet: Packet) -> None:
        """De-multiplex [clientid + 32 B record] blocks per client
        (GateService.go:346-371) — vectorized: one structured-array view,
        then each maximal run of equal clientids leaves as a single
        contiguous ``tobytes()`` slice. The game packs each collection's
        rows grouped by destination client (slabs.py collect_sync_selection
        orders by destination slot), so the adjacent-run scan recovers the
        per-client grouping without the argsort this path used to pay; an
        ungrouped producer only costs extra (smaller) sends, never a wrong
        route. Wall time lands on
        fanout_hop_seconds_total{hop="gate_demux"} (the corked client
        writes themselves are costed under client_write at the
        end-of-batch uncork sweep)."""
        t0 = time.perf_counter()
        packet.read_uint16()  # gateid
        data = packet.read_rest()  # raw [clientid + record] blocks
        k = len(data) // _CLIENT_BLOCK_SIZE
        if not k:
            return
        arr = np.frombuffer(data, CLIENT_SYNC_DTYPE, count=k)
        cids = arr["cid"]
        if k == 1:
            cp = self.clients.get(cids[0].decode("ascii"))
            if cp is not None:
                cp.send(MsgType.SYNC_POSITION_YAW_ON_CLIENTS,
                        arr["rec"].tobytes())
            _HOP_GATE_DEMUX.inc(time.perf_counter() - t0)
            return
        rec = arr["rec"]
        bounds = [0] + (np.flatnonzero(cids[1:] != cids[:-1]) + 1).tolist() + [k]
        clients = self.clients
        for i in range(len(bounds) - 1):
            lo, hi = bounds[i], bounds[i + 1]
            cp = clients.get(cids[lo].decode("ascii"))
            if cp is not None:
                cp.send(MsgType.SYNC_POSITION_YAW_ON_CLIENTS,
                        rec[lo:hi].tobytes())
        _HOP_GATE_DEMUX.inc(time.perf_counter() - t0)

    def _handle_sync_delta_on_clients(self, packet: Packet) -> None:
        """De-multiplex the v6 quantized-delta variant: [u16 gateid]
        [u8 quantize_bits] + fixed 40 B [clientid + 24 B delta record]
        blocks, same vectorized run-slicing as the full-precision demux.
        Each client's forward re-carries the quantize_bits header byte so
        the client decode stays self-describing — one small concat per
        RUN, not per record."""
        t0 = time.perf_counter()
        packet.read_uint16()  # gateid
        qb = packet.read_byte()
        data = packet.read_rest()
        k = len(data) // _CLIENT_DELTA_BLOCK_SIZE
        if not k:
            return
        header = bytes((qb,))
        arr = np.frombuffer(data, CLIENT_DELTA_SYNC_DTYPE, count=k)
        cids = arr["cid"]
        rec = arr["rec"]
        bounds = [0] + (np.flatnonzero(cids[1:] != cids[:-1]) + 1).tolist() + [k]
        clients = self.clients
        for i in range(len(bounds) - 1):
            lo, hi = bounds[i], bounds[i + 1]
            cp = clients.get(cids[lo].decode("ascii"))
            if cp is not None:
                cp.send(MsgType.SYNC_POSITION_YAW_DELTA_ON_CLIENTS,
                        header + rec[lo:hi].tobytes())
        _HOP_GATE_DEMUX.inc(time.perf_counter() - t0)

    # --- filter props (FilterTree.go, GateService.go:300-344) ----------------

    def _set_filter_prop(self, cp: ClientProxy, key: str, val: str) -> None:
        old = cp.filter_props.get(key)
        tree = self.filter_trees.get(key)
        if tree is None:
            tree = self.filter_trees[key] = FilterTree()
        if old is not None:
            tree.remove(old, cp.clientid)
        cp.filter_props[key] = val
        tree.insert(val, cp.clientid)

    def _clear_filter_props(self, cp: ClientProxy) -> None:
        for key, val in cp.filter_props.items():
            tree = self.filter_trees.get(key)
            if tree is not None:
                tree.remove(val, cp.clientid)
        cp.filter_props.clear()

    def _handle_call_filtered_clients(self, packet: Packet) -> None:
        op = FilterOp(packet.read_byte())
        key = packet.read_varstr()
        val = packet.read_varstr()
        payload = packet.read_rest()  # [method][args] forwarded verbatim
        if key == "":
            # Empty key = every client on this gate (GateService.go:378-384,
            # the "world channel" broadcast).
            for cp in list(self.clients.values()):
                cp.send(MsgType.CALL_FILTERED_CLIENTS, payload)
            return
        tree = self.filter_trees.get(key)
        if tree is None:
            return
        for clientid in list(tree.visit(op, val)):
            cp = self.clients.get(clientid)
            if cp is not None:
                cp.send(MsgType.CALL_FILTERED_CLIENTS, payload)


def run(gateid: int | None = None) -> int:
    """Process entry point (gate.go:46-55)."""
    import argparse

    from goworld_tpu.config import get as get_config, set_config_file

    parser = argparse.ArgumentParser(description="goworld_tpu gate process")
    parser.add_argument("-gid", type=int, default=gateid or 1)
    parser.add_argument("-configfile", type=str, default="")
    parser.add_argument("-log", type=str, default="")
    parser.add_argument("-d", action="store_true", help="daemonize")
    args, _ = parser.parse_known_args()
    if args.configfile:
        set_config_file(args.configfile)
    cfg = get_config()
    gate_cfg = cfg.gates.get(args.gid)
    if args.d:
        from goworld_tpu.utils.binutil import daemonize

        daemonize((gate_cfg.log_file if gate_cfg else None)
                  or f"gate{args.gid}.daemon.log")
    gwlog.setup(
        level=(args.log or (gate_cfg.log_level if gate_cfg else "info")),
        logfile=(gate_cfg.log_file if gate_cfg else None) or None,
        fmt=cfg.log.format,
    )
    gwlog.set_source(f"gate{args.gid}")
    svc = GateService(args.gid, cfg)

    async def main() -> int:
        import signal

        loop = asyncio.get_running_loop()
        try:
            loop.add_signal_handler(signal.SIGTERM, svc.terminate)
        except (NotImplementedError, RuntimeError):
            pass
        return await svc.run_async()

    return asyncio.run(main())

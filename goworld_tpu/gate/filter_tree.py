"""Ordered filter-prop index for filtered client broadcast.

Reference parity: ``components/gate/FilterTree.go:12-102`` — the gate keeps,
per filter key, an ordered tree of (value, clientid) pairs so that
``CallFilteredClients(op, key, val)`` can visit clients whose prop compares to
``val`` under any of =, !=, <, <=, >, >= (proto.go:142-151). The reference
uses an LLRB tree; a bisect-maintained sorted list gives the same ordered
visits with O(log n) seek (string comparison order, as in the reference).
"""

from __future__ import annotations

import bisect
from typing import Callable, Iterator

from goworld_tpu.proto.msgtypes import FilterOp


class FilterTree:
    """Ordered (value, clientid) index for ONE filter key."""

    def __init__(self) -> None:
        # Sorted by (val, clientid); clientids are unique within a tree
        # because ClientProxy removes its old value before inserting a new one.
        self._items: list[tuple[str, str]] = []

    def __len__(self) -> int:
        return len(self._items)

    def insert(self, val: str, clientid: str) -> None:
        bisect.insort(self._items, (val, clientid))

    def remove(self, val: str, clientid: str) -> bool:
        i = bisect.bisect_left(self._items, (val, clientid))
        if i < len(self._items) and self._items[i] == (val, clientid):
            self._items.pop(i)
            return True
        return False

    # --- ordered visits (FilterTree.go:40-102) -----------------------------

    def visit(self, op: FilterOp, val: str) -> Iterator[str]:
        """Yield clientids whose stored value compares to ``val`` under
        ``op``. String comparison, matching the reference's tree order."""
        items = self._items
        lo = bisect.bisect_left(items, (val, ""))
        # First index whose value is strictly greater than val: (val+"\x00", "")
        # sorts after every (val, clientid) and before any larger value.
        hi = bisect.bisect_left(items, (val + "\x00", ""))
        if op == FilterOp.EQ:
            rng: Iterator[tuple[str, str]] = iter(items[lo:hi])
        elif op == FilterOp.NE:
            rng = iter(items[:lo] + items[hi:])
        elif op == FilterOp.LT:
            rng = iter(items[:lo])
        elif op == FilterOp.LTE:
            rng = iter(items[:hi])
        elif op == FilterOp.GT:
            rng = iter(items[hi:])
        elif op == FilterOp.GTE:
            rng = iter(items[lo:])
        else:  # pragma: no cover - exhaustive over FilterOp
            raise ValueError(f"bad filter op {op}")
        for _, clientid in rng:
            yield clientid

    def visit_each(self, op: FilterOp, val: str, fn: Callable[[str], None]) -> None:
        for cid in list(self.visit(op, val)):
            fn(cid)

"""Message type space.

Reference parity: ``engine/proto/proto.go:19-151``. The numeric ranges are
semantic routing classes (the dispatcher routes by range, not by individual
type — DispatcherService.go:214-285):

- 1..999:      handled by the dispatcher itself
- 1001..1499:  "redirect" range — game→dispatcher→gate→client; payload starts
               with [u16 gateid][clientid] which the gate strips
- 1501..1999:  handled by the gate (broadcast/filtered operations)
- 2001..:      gate↔client only
"""

from __future__ import annotations

import enum

# Cluster wire-protocol version, carried in the SET_GAME_ID / SET_GATE_ID
# handshakes and verified by the dispatcher. Bump on ANY payload layout
# change (e.g. the round-3 migrate-nonce addition) so a mixed-version
# dispatcher/game pair — mid rolling upgrade, or a dispatcher not restarted
# during `reload` — fails loudly at connect instead of mis-framing packets.
#
# The AUTHORITATIVE payload layouts live in proto/schema.py (one field
# sequence per MsgType), checked against every pack/unpack site by gwlint
# R7 — which also pins a digest of the whole table per version
# (SCHEMA_HISTORY), so forgetting this bump on a layout change fails the
# lint instead of a production rollout.  The per-version notes below stay
# as the human changelog of WHY each bump happened.
# v3: cluster-link HEARTBEAT + liveness kills — a v2 peer would neither
# send heartbeats nor expect them, so a v3 end would kill its (healthy)
# idle links; fail the mixed pair at the handshake instead.
# v4: optional distributed-tracing trailer on cluster packets — a SAMPLED
# packet sets MSGTYPE_TRACE_FLAG (bit 15 of the u16 msgtype, far above
# every type id) and appends a 17-byte TraceContext after the payload,
# stripped at the recv seam (telemetry/tracing.py). Unsampled packets and
# HEARTBEAT are byte-identical to v3, but a v3 peer would route a flagged
# msgtype to "unhandled" and mis-read the trailer as payload bytes — fail
# the mixed pair at the handshake instead.
# v5: rebalancing + crash hygiene — SET_GATE_ID gains a ``fresh`` bool
# BEFORE the version field (a restarted gate process announces itself so
# the dispatcher can detach its dead predecessor's client bindings — a v4
# dispatcher would mis-read the bool as the version's first byte), plus
# the new GAME_LOAD_REPORT / REBALANCE_MIGRATE types a v4 peer would drop
# as unhandled.
# v6: adaptive per-client sync — the new SYNC_POSITION_YAW_DELTA_ON_CLIENTS
# type carries quantized position DELTAS against per-client baselines
# ([u16 gateid][u8 quantize_bits] + fixed 40 B [cid + delta record]
# blocks, proto/conn.py CLIENT_DELTA_SYNC_DTYPE). A v5 gate would drop
# the type as unhandled and its clients would silently stop seeing
# tiered neighbors move — fail the mixed pair at the handshake instead.
# v7: whole-space migration + crash-survivable rebalance plane — the new
# SPACE_MIGRATE_PREPARE / PREPARE_ACK / DATA / ABORT / ACK handoff types,
# REBALANCE_MIGRATE_SPACE commands, and the planner-service REBALANCE_PLAN
# push. A v6 peer would drop every one as unhandled, silently wedging a
# space handoff mid-PREPARE (members parked until the deadline on every
# round) — fail the mixed pair at the handshake instead.
PROTO_VERSION = 7

# High bit of the wire msgtype: a tracing trailer follows the payload.
# Never a routing class — masked off before any msgtype comparison.
MSGTYPE_TRACE_FLAG = 0x8000


class MsgType(enum.IntEnum):
    # --- dispatcher-handled (proto.go:19-76) -------------------------------
    SET_GAME_ID = 1
    SET_GAME_ID_ACK = 2
    SET_GATE_ID = 3
    NOTIFY_CREATE_ENTITY = 4
    NOTIFY_DESTROY_ENTITY = 5
    NOTIFY_CLIENT_CONNECTED = 6
    NOTIFY_CLIENT_DISCONNECTED = 7
    CALL_ENTITY_METHOD = 8
    CALL_ENTITY_METHOD_FROM_CLIENT = 9
    QUERY_SPACE_GAMEID_FOR_MIGRATE = 10
    QUERY_SPACE_GAMEID_FOR_MIGRATE_ACK = 11
    MIGRATE_REQUEST = 12
    MIGRATE_REQUEST_ACK = 13
    REAL_MIGRATE = 14
    CANCEL_MIGRATE = 15
    LOAD_ENTITY_SOMEWHERE = 16
    CREATE_ENTITY_SOMEWHERE = 17
    CALL_NIL_SPACES = 18
    SYNC_POSITION_YAW_FROM_CLIENT = 19
    NOTIFY_GAME_CONNECTED = 20
    NOTIFY_GAME_DISCONNECTED = 21
    NOTIFY_GATE_DISCONNECTED = 22
    NOTIFY_DEPLOYMENT_READY = 23
    START_FREEZE_GAME = 24
    START_FREEZE_GAME_ACK = 25
    KVREG_REGISTER = 26
    GAME_LBC_INFO = 27
    # Cluster-link liveness probe (no reference analog — GoWorld has
    # heartbeats only on gate↔client): sent on idle game/gate↔dispatcher
    # links by BOTH ends, swallowed at the recv seam (never queued to
    # logic); its only effect is refreshing the peer's last-seen clock.
    HEARTBEAT = 28
    # Rich per-game load report (no reference analog; supersedes the
    # cpu-only GAME_LBC_INFO, which stays wired for reference parity):
    # one bson dict per second per game — cpu%, entity count, tick-phase
    # p95, queue depth, per-space populations — feeding both the LBC
    # choose-game heap and the dispatcher-side rebalancer (rebalance/).
    GAME_LOAD_REPORT = 29
    # Dispatcher→game rebalance command: migrate up to ``count`` entities
    # out of one space into a same-kind space on another game via the
    # hardened cross-game migration path (rebalance/migrator.py).
    REBALANCE_MIGRATE = 30
    # --- whole-space migration (ISSUE 18; no reference analog — GoWorld
    # never moves a live space).  The handoff is freeze-fence + fat
    # transfer: PREPARE broadcast parks the listed members' streams on
    # every owning dispatcher, each acks on its own FIFO (the freeze-ack
    # fence), the donor packs only after every ack, and the one DATA
    # payload routes exactly like REAL_MIGRATE — buffer behind a grace
    # window, bounce HOME to the donor on a dead target.  Proved in
    # analysis/modelcheck.py (space_handoff / space_member_race) BEFORE
    # this implementation landed.
    # Donor game → EVERY dispatcher: freeze announcement + member list.
    SPACE_MIGRATE_PREPARE = 31
    # Each dispatcher → donor game, after parking its listed members.
    SPACE_MIGRATE_PREPARE_ACK = 32
    # Donor → space-owner dispatcher → receiver game: the whole-space
    # snapshot, with a source-game trailer for the bounce-home path.
    SPACE_MIGRATE_DATA = 33
    # Abort, either direction: dispatcher→donor (target dead at
    # PREPARE) or donor→dispatchers (deadline fired; unpark members).
    SPACE_MIGRATE_ABORT = 34
    # Receiver game → space-owner dispatcher: restore completed
    # (telemetry + handoff-entry cleanup; routing rides NOTIFY_CREATE).
    SPACE_MIGRATE_ACK = 35
    # Dispatcher → donor game: move one whole space to another game
    # (the bin-packer's whole-space analog of REBALANCE_MIGRATE).
    REBALANCE_MIGRATE_SPACE = 36
    # Planner-service game → its owner dispatcher: an externally
    # computed rebalance plan to validate and dispatch (planner
    # failover rides the sharded-service plane, ISSUE 18).
    REBALANCE_PLAN = 37

    # --- redirected to client via gate (proto.go:85-114) -------------------
    CREATE_ENTITY_ON_CLIENT = 1001
    DESTROY_ENTITY_ON_CLIENT = 1002
    NOTIFY_MAP_ATTR_CHANGE_ON_CLIENT = 1003
    NOTIFY_MAP_ATTR_DEL_ON_CLIENT = 1004
    NOTIFY_MAP_ATTR_CLEAR_ON_CLIENT = 1005
    NOTIFY_LIST_ATTR_CHANGE_ON_CLIENT = 1006
    NOTIFY_LIST_ATTR_POP_ON_CLIENT = 1007
    NOTIFY_LIST_ATTR_APPEND_ON_CLIENT = 1008
    CALL_ENTITY_METHOD_ON_CLIENT = 1009
    SET_CLIENTPROXY_FILTER_PROP = 1010
    CLEAR_CLIENTPROXY_FILTER_PROPS = 1011

    # --- gate-handled (proto.go:116-123) -----------------------------------
    CALL_FILTERED_CLIENTS = 1501
    SYNC_POSITION_YAW_ON_CLIENTS = 1502
    # Compact sync variant (no reference analog; ROADMAP item 5): quantized
    # position deltas against a per-client baseline, sent beside the full-
    # precision keyframes that ride SYNC_POSITION_YAW_ON_CLIENTS. The
    # payload self-describes its quantization step ([u8 quantize_bits]
    # after the gateid) so gates and clients need no config coupling.
    SYNC_POSITION_YAW_DELTA_ON_CLIENTS = 1503

    # --- gate↔client direct (proto.go:126-133) -----------------------------
    HEARTBEAT_FROM_CLIENT = 2001


REDIRECT_MIN = 1001
REDIRECT_MAX = 1499
GATE_MIN = 1501
GATE_MAX = 1999
CLIENT_MIN = 2001


def is_dispatcher_handled(t: int) -> bool:  # gwlint: keep — msgtype classification API beside is_gate_*
    return t < 1000


def is_gate_redirect(t: int) -> bool:
    return REDIRECT_MIN <= t <= REDIRECT_MAX


def is_gate_handled(t: int) -> bool:  # gwlint: keep — msgtype classification API beside is_gate_redirect
    return GATE_MIN <= t <= GATE_MAX


class FilterOp(enum.IntEnum):
    """Filtered-client broadcast comparison ops (proto.go:142-151)."""

    EQ = 0
    NE = 1
    LT = 2
    LTE = 3
    GT = 4
    GTE = 5

"""Typed message senders over a PacketConnection.

Reference parity: ``engine/proto/GoWorldConnection.go:16-497`` — one SendXxx
method per message type, so payload layouts live in exactly one place.
Position-sync records are fixed 32 B = EntityID(16) + x,y,z,yaw float32
(proto.go:135-139).
"""

from __future__ import annotations

import struct

import numpy as np

from goworld_tpu.netutil.packet import Packet
from goworld_tpu.netutil.packet_conn import PacketConnection
from goworld_tpu.proto.msgtypes import (
    MSGTYPE_TRACE_FLAG,
    PROTO_VERSION,
    FilterOp,
    MsgType,
)
from goworld_tpu.telemetry import tracing as _tracing

SYNC_RECORD_SIZE = 16 + 4 * 4  # EntityID + x,y,z,yaw (proto.go:135-139)
_SYNC = struct.Struct("<16s4f")

# Numpy views of the same wire layouts (packed — field offsets match the
# struct formats byte for byte), used by the batch pack/unpack paths: one
# C-level conversion per tick instead of one struct call per record.
SYNC_DTYPE = np.dtype(
    [("eid", "S16"), ("x", "<f4"), ("y", "<f4"), ("z", "<f4"),
     ("yaw", "<f4")]
)
# [clientid(16) + sync record] block (game→dispatcher→gate). The record
# half is kept as one opaque 32 B field so the gate's demux can slice
# per-client record runs with a single .tobytes() per client.
CLIENT_SYNC_DTYPE = np.dtype([("cid", "S16"), ("rec", "V32")])
# The same wire block with the record half split into named fields — the
# layout the columnar sync collect fills by column assignment
# (entity/slabs.py pack_sync; pack_client_sync_columns below).
CLIENT_SYNC_BLOCK_DTYPE = np.dtype(
    [("cid", "S16"), ("eid", "S16"), ("x", "<f4"), ("y", "<f4"),
     ("z", "<f4"), ("yaw", "<f4")]
)
assert SYNC_DTYPE.itemsize == SYNC_RECORD_SIZE
assert CLIENT_SYNC_DTYPE.itemsize == 16 + SYNC_RECORD_SIZE
assert CLIENT_SYNC_BLOCK_DTYPE.itemsize == 16 + SYNC_RECORD_SIZE

# --- v6 compact sync records (adaptive per-client sync, ROADMAP item 5) ------
# Quantized position DELTAS against a per-client baseline: EntityID(16) +
# dx,dy,dz,dyaw int16, each in units of 2^-quantize_bits (the packet
# header names the step, proto/schema.py). 24 B on the client wire vs the
# full record's 32 B; the real win is the cadence tiers gating how often
# a record is emitted at all (entity/slabs.py).
DELTA_SYNC_RECORD_SIZE = 16 + 4 * 2
DELTA_SYNC_DTYPE = np.dtype(
    [("eid", "S16"), ("dx", "<i2"), ("dy", "<i2"), ("dz", "<i2"),
     ("dyaw", "<i2")]
)
# [clientid(16) + 24 B delta record] block (game→dispatcher→gate); the
# record half stays opaque so the gate demux slices per-client runs with
# one tobytes() per client, exactly like CLIENT_SYNC_DTYPE.
CLIENT_DELTA_SYNC_DTYPE = np.dtype([("cid", "S16"), ("rec", "V24")])
# The same block with named fields — the layout the tiered columnar
# collect fills by column assignment (entity/slabs.py).
CLIENT_DELTA_SYNC_BLOCK_DTYPE = np.dtype(
    [("cid", "S16"), ("eid", "S16"), ("dx", "<i2"), ("dy", "<i2"),
     ("dz", "<i2"), ("dyaw", "<i2")]
)
assert DELTA_SYNC_DTYPE.itemsize == DELTA_SYNC_RECORD_SIZE
assert CLIENT_DELTA_SYNC_DTYPE.itemsize == 16 + DELTA_SYNC_RECORD_SIZE
assert CLIENT_DELTA_SYNC_BLOCK_DTYPE.itemsize == 16 + DELTA_SYNC_RECORD_SIZE

# Process-wide wire volume (telemetry): counted HERE because every peer
# connection of every process — dispatcher↔game/gate streams AND gate
# client conns over TCP/WS/KCP — goes through GoWorldConnection, so one
# seam covers all transports. Direction-labeled totals rather than
# per-connection series: connections churn (one label set per client
# would grow the registry unboundedly); per-service breakdowns come from
# the queue/client gauges beside them. Children are pre-resolved so the
# per-packet hot path is a single Counter.inc.
from goworld_tpu import telemetry as _telemetry

_PKT = _telemetry.counter(
    "net_packets_total",
    "Framed packets through GoWorldConnection (all transports).",
    ("direction",))
_BYTES = _telemetry.counter(
    "net_bytes_total",
    "Framed payload bytes through GoWorldConnection (pre-compression).",
    ("direction",))
_PKT_IN, _PKT_OUT = _PKT.labels("in"), _PKT.labels("out")
_BYTES_IN, _BYTES_OUT = _BYTES.labels("in"), _BYTES.labels("out")


def pack_sync_record(eid: str, x: float, y: float, z: float, yaw: float) -> bytes:
    return _SYNC.pack(eid.encode("ascii"), x, y, z, yaw)


def unpack_sync_records(data: bytes) -> list[tuple[str, float, float, float, float]]:
    """Decode concatenated 32 B sync records — one vectorized frombuffer
    instead of a struct.unpack per record (same tuples, same float32
    rounding). A trailing partial record is ignored, as the struct loop
    before it would have raised only on a *fully* malformed tail."""
    k = len(data) // SYNC_RECORD_SIZE
    if not k:
        return []
    arr = np.frombuffer(data, SYNC_DTYPE, count=k)
    return list(
        zip(
            [e.decode("ascii") for e in arr["eid"].tolist()],
            arr["x"].tolist(),
            arr["y"].tolist(),
            arr["z"].tolist(),
            arr["yaw"].tolist(),
        )
    )


def pack_client_sync_blocks(
    rows: list[tuple[str, str, float, float, float, float]]
) -> bytes:
    """Batch-pack [clientid(16) + 32 B sync record] blocks from
    (clientid, eid, x, y, z, yaw) rows — ONE structured-array conversion
    per gate per tick (the game's sync fan-out hot path) instead of a
    struct.pack + bytearray append per record."""
    if not rows:
        return b""
    return np.array(rows, dtype=CLIENT_SYNC_BLOCK_DTYPE).tobytes()


def pack_client_delta_sync_blocks(
    rows: list[tuple[str, str, int, int, int, int]]
) -> bytes:
    """Batch-pack [clientid(16) + 24 B delta record] blocks from
    (clientid, eid, dx, dy, dz, dyaw) rows of pre-quantized int16 deltas
    (tests + the schema fuzz seed; the hot path fills
    CLIENT_DELTA_SYNC_BLOCK_DTYPE by column assignment in slabs.py)."""
    if not rows:
        return b""
    return np.array(rows, dtype=CLIENT_DELTA_SYNC_BLOCK_DTYPE).tobytes()


def pack_client_sync_columns(cid: np.ndarray, eid: np.ndarray,
                             x: np.ndarray, y: np.ndarray,
                             z: np.ndarray, yaw: np.ndarray) -> bytes:
    """Columnar variant of :func:`pack_client_sync_blocks`: fill the wire
    blocks by column assignment from parallel arrays (the slab store's
    collect path builds its per-gate buffers this way — zero Python row
    tuples; this helper is the standalone seam for tests and tools)."""
    out = np.empty(len(cid), CLIENT_SYNC_BLOCK_DTYPE)
    out["cid"] = cid
    out["eid"] = eid
    out["x"] = x
    out["y"] = y
    out["z"] = z
    out["yaw"] = yaw
    return out.tobytes()


class GoWorldConnection:
    """Wraps a PacketConnection with typed senders.

    ``trace_wire=True`` (cluster links only: game/gate↔dispatcher, both
    directions) piggybacks the active sampled TraceContext as a 17-byte
    packet trailer flagged by MSGTYPE_TRACE_FLAG — absent for unsampled
    packets, so the untraced fast path pays exactly one branch per send
    and the wire stays byte-identical to v3 framing. The recv seam strips
    the trailer on ANY connection (ignored-compatible), attaching the
    context to ``packet.trace``. Gate↔client links keep trace_wire off:
    the client protocol is unchanged and traces terminate at the gate's
    fan-out span.
    """

    def __init__(self, conn: PacketConnection, *,
                 trace_wire: bool = False) -> None:
        self.conn = conn
        self.trace_wire = trace_wire

    # --- generic -----------------------------------------------------------

    def _trace_ctx(
        self, packet_trace: "_tracing.TraceContext | None"
    ) -> "_tracing.TraceContext | None":
        """The context to piggyback: the active span's, else the one the
        packet itself arrived with (dispatcher buffered/replayed forwards
        outside any handling scope must not lose the trace)."""
        ctx = _tracing.current()
        return ctx if ctx is not None else packet_trace

    def send(self, msgtype: int, packet: Packet) -> None:
        _PKT_OUT.inc()
        _BYTES_OUT.inc(packet.payload_len())
        if self.trace_wire:
            ctx = self._trace_ctx(packet.trace)
            if ctx is not None:
                # Copy-on-trace: broadcasts reuse one Packet across links,
                # so the original payload must stay trailer-free.
                self.conn.send_packet(
                    msgtype | MSGTYPE_TRACE_FLAG,
                    Packet(packet.payload + _tracing.encode_trailer(ctx)))
                return
        self.conn.send_packet(msgtype, packet)

    def send_packet_raw(self, msgtype: int, payload: bytes) -> None:
        _PKT_OUT.inc()
        _BYTES_OUT.inc(len(payload))
        if self.trace_wire:
            ctx = self._trace_ctx(None)
            if ctx is not None:
                self.conn.send_packet(
                    msgtype | MSGTYPE_TRACE_FLAG,
                    Packet(payload + _tracing.encode_trailer(ctx)))
                return
        self.conn.send_packet(msgtype, Packet(payload))

    async def recv(self) -> tuple[int, Packet]:
        msgtype, packet = await self.conn.recv_packet()
        _PKT_IN.inc()
        _BYTES_IN.inc(packet.payload_len())
        if msgtype & MSGTYPE_TRACE_FLAG:
            msgtype &= ~MSGTYPE_TRACE_FLAG
            if packet.payload_len() >= _tracing.TRAILER_SIZE:
                packet.trace = _tracing.decode_trailer(
                    packet.pop_tail(_tracing.TRAILER_SIZE))
        return msgtype, packet

    def flush(self) -> None:
        self.conn.flush()

    def cork(self) -> None:
        """Tick-scoped write coalescing, where the transport supports it
        (TCP PacketConnection). KCP coalesces in stream mode and WS has a
        dedicated writer task, so for those this is a no-op."""
        fn = getattr(self.conn, "cork", None)
        if fn is not None:
            fn()

    def uncork(self) -> None:
        fn = getattr(self.conn, "uncork", None)
        if fn is not None:
            fn()

    def close(self) -> None:
        self.conn.close()

    @property
    def closed(self) -> bool:
        return self.conn.closed

    # --- handshakes --------------------------------------------------------

    def send_set_game_id(
        self,
        gameid: int,
        is_reconnect: bool,
        is_restore: bool,
        is_ban_boot_entity: bool,
        entity_ids: list[str],
    ) -> None:
        """Game→dispatcher handshake (DispatcherConnMgr.go:66-88); carries the
        game's live entity list for reconnect reconciliation
        (DispatcherService.go:327-402)."""
        p = Packet()
        p.append_uint16(gameid)
        p.append_bool(is_reconnect)
        p.append_bool(is_restore)
        p.append_bool(is_ban_boot_entity)
        p.append_data(entity_ids)
        p.append_uint32(PROTO_VERSION)
        self.send(MsgType.SET_GAME_ID, p)

    def send_set_game_id_ack(
        self,
        online_games: list[int],
        rejected_entity_ids: list[str],
        kvreg_map: dict[str, str],
        deployment_ready: bool,
    ) -> None:
        p = Packet()
        p.append_data(
            {
                "online_games": online_games,
                "rejected": rejected_entity_ids,
                "kvreg": kvreg_map,
                "ready": deployment_ready,
            }
        )
        self.send(MsgType.SET_GAME_ID_ACK, p)

    def send_set_gate_id(self, gateid: int, fresh: bool = False,
                         gen: int = 0) -> None:
        """``fresh`` = this is a brand-new gate process introducing itself
        (not a surviving gate re-dialing after a link blip): the dispatcher
        then detaches the dead predecessor's client bindings on every game
        before registering the new proxy (stale GameClient bindings would
        otherwise route syncs/RPCs at clientids no socket serves).
        ``gen`` = the gate process's boot generation; the detach broadcast
        names it as the VALID generation so a late-arriving broadcast can
        never detach clients that connected through the new process."""
        p = Packet()
        p.append_uint16(gateid)
        p.append_bool(fresh)
        p.append_uint32(gen)
        p.append_uint32(PROTO_VERSION)
        self.send(MsgType.SET_GATE_ID, p)

    # --- entity lifecycle notifications ------------------------------------

    def send_notify_create_entity(self, eid: str) -> None:
        p = Packet()
        p.append_entity_id(eid)
        self.send(MsgType.NOTIFY_CREATE_ENTITY, p)

    def send_notify_destroy_entity(self, eid: str) -> None:
        p = Packet()
        p.append_entity_id(eid)
        self.send(MsgType.NOTIFY_DESTROY_ENTITY, p)

    # --- client lifecycle --------------------------------------------------

    def send_notify_client_connected(self, clientid: str, gateid: int,
                                     boot_eid: str, gate_gen: int = 0) -> None:
        p = Packet()
        p.append_client_id(clientid)
        p.append_uint16(gateid)
        p.append_entity_id(boot_eid)
        # Gate boot generation LAST (the dispatcher's boot-eid peek reads
        # the prefix positionally): pairs with NOTIFY_GATE_DISCONNECTED's
        # valid-generation field (GameClient.gate_gen).
        p.append_uint32(gate_gen)
        self.send(MsgType.NOTIFY_CLIENT_CONNECTED, p)

    def send_notify_client_disconnected(self, clientid: str, owner_eid: str) -> None:
        p = Packet()
        p.append_client_id(clientid)
        p.append_entity_id(owner_eid)
        self.send(MsgType.NOTIFY_CLIENT_DISCONNECTED, p)

    # --- RPC ---------------------------------------------------------------

    def send_call_entity_method(self, eid: str, method: str, args: tuple) -> None:
        p = Packet()
        p.append_entity_id(eid)
        p.append_varstr(method)
        p.append_args(args)
        self.send(MsgType.CALL_ENTITY_METHOD, p)

    def send_call_entity_method_from_client(
        self, eid: str, method: str, args: tuple, clientid: str
    ) -> None:
        p = Packet()
        p.append_entity_id(eid)
        p.append_varstr(method)
        p.append_args(args)
        p.append_client_id(clientid)
        self.send(MsgType.CALL_ENTITY_METHOD_FROM_CLIENT, p)

    def send_call_nil_spaces(self, except_game: int, method: str, args: tuple) -> None:
        p = Packet()
        p.append_uint16(except_game)
        p.append_varstr(method)
        p.append_args(args)
        self.send(MsgType.CALL_NIL_SPACES, p)

    # --- create/load somewhere ---------------------------------------------

    def send_create_entity_somewhere(self, gameid: int, typename: str, eid: str, attrs: dict) -> None:
        """gameid 0 = dispatcher picks the least-loaded game
        (DispatcherService.go:529-542)."""
        p = Packet()
        p.append_uint16(gameid)
        p.append_varstr(typename)
        p.append_entity_id(eid)
        p.append_data(attrs)
        self.send(MsgType.CREATE_ENTITY_SOMEWHERE, p)

    def send_load_entity_somewhere(self, typename: str, eid: str, gameid: int) -> None:
        p = Packet()
        p.append_uint16(gameid)
        p.append_varstr(typename)
        p.append_entity_id(eid)
        self.send(MsgType.LOAD_ENTITY_SOMEWHERE, p)

    # --- migration (Entity.go:956-1115, DispatcherService.go:850-907) ------

    # The migration query/request acks carry a per-request NONCE, echoed
    # verbatim by the dispatcher: ack validity must bind to the exact
    # request instance, not just the space id — a stale buffered ack for a
    # canceled request must never satisfy a newer same-space request (its
    # dispatcher block was released by the cancel).

    def send_query_space_gameid_for_migrate(
        self, spaceid: str, eid: str, nonce: int = 0
    ) -> None:
        p = Packet()
        p.append_entity_id(spaceid)
        p.append_entity_id(eid)
        p.append_uint32(nonce)
        self.send(MsgType.QUERY_SPACE_GAMEID_FOR_MIGRATE, p)

    def send_query_space_gameid_for_migrate_ack(
        self, spaceid: str, eid: str, gameid: int, nonce: int = 0
    ) -> None:
        p = Packet()
        p.append_entity_id(spaceid)
        p.append_entity_id(eid)
        p.append_uint16(gameid)
        p.append_uint32(nonce)
        self.send(MsgType.QUERY_SPACE_GAMEID_FOR_MIGRATE_ACK, p)

    def send_migrate_request(
        self, eid: str, spaceid: str, space_gameid: int, nonce: int = 0
    ) -> None:
        p = Packet()
        p.append_entity_id(eid)
        p.append_entity_id(spaceid)
        p.append_uint16(space_gameid)
        p.append_uint32(nonce)
        self.send(MsgType.MIGRATE_REQUEST, p)

    def send_migrate_request_ack(
        self, eid: str, spaceid: str, space_gameid: int, nonce: int = 0
    ) -> None:
        p = Packet()
        p.append_entity_id(eid)
        p.append_entity_id(spaceid)
        p.append_uint16(space_gameid)
        p.append_uint32(nonce)
        self.send(MsgType.MIGRATE_REQUEST_ACK, p)

    def send_real_migrate(self, eid: str, target_game: int,
                          migrate_data: dict, source_game: int = 0) -> None:
        """``source_game`` rides as a TRAILING u16 so the dispatcher can
        bounce the payload home without parsing the bson body — the
        packet is the entity's only copy, and when the target game turns
        out dead the sender's identity may no longer be derivable from
        the connection (a sweep-time bounce happens long after the
        forwarding proxy is gone)."""
        p = Packet()
        p.append_entity_id(eid)
        p.append_uint16(target_game)
        p.append_data(migrate_data)
        p.append_uint16(source_game)
        self.send(MsgType.REAL_MIGRATE, p)

    def send_cancel_migrate(self, eid: str) -> None:
        p = Packet()
        p.append_entity_id(eid)
        self.send(MsgType.CANCEL_MIGRATE, p)

    # --- position sync -----------------------------------------------------

    def send_sync_position_yaw_from_client(self, records: bytes) -> None:
        """records = concatenated 32 B sync records (gate→dispatcher,
        GateService.go:398-425)."""
        self.send_packet_raw(MsgType.SYNC_POSITION_YAW_FROM_CLIENT, records)

    def send_sync_position_yaw_on_clients(self, gateid: int, records: bytes) -> None:
        """records = concatenated [clientid(16) + 32 B sync record] blocks
        (game→dispatcher→gate, Entity.go:1221-1267). Built as one bytes
        payload so the Packet rides the zero-copy constructor (the sync
        fan-out's largest per-tick buffer pays exactly one copy here)."""
        self.send(MsgType.SYNC_POSITION_YAW_ON_CLIENTS,
                  Packet(struct.pack("<H", gateid) + records))

    def send_sync_position_yaw_delta_on_clients(
        self, gateid: int, quantize_bits: int, records: bytes
    ) -> None:
        """records = concatenated [clientid(16) + 24 B delta record]
        blocks (the v6 compact sync variant). ``quantize_bits`` rides the
        payload so the gate/client decode is self-describing: deltas are
        int16 multiples of 2^-quantize_bits world units."""
        self.send(MsgType.SYNC_POSITION_YAW_DELTA_ON_CLIENTS,
                  Packet(struct.pack("<HB", gateid, quantize_bits) + records))

    # --- process / deployment events ---------------------------------------

    def send_notify_game_connected(self, gameid: int) -> None:
        p = Packet()
        p.append_uint16(gameid)
        self.send(MsgType.NOTIFY_GAME_CONNECTED, p)

    def send_notify_game_disconnected(self, gameid: int) -> None:
        p = Packet()
        p.append_uint16(gameid)
        self.send(MsgType.NOTIFY_GAME_DISCONNECTED, p)

    def send_notify_gate_disconnected(self, gateid: int,
                                      valid_gen: int = 0) -> None:
        """``valid_gen`` != 0 narrows the detach to clients of OTHER gate
        generations (the gate process restarted: its old clients are dead
        but its new ones — which carry valid_gen — are live). 0 = the
        gate is gone entirely; detach every client of that gateid."""
        p = Packet()
        p.append_uint16(gateid)
        p.append_uint32(valid_gen)
        self.send(MsgType.NOTIFY_GATE_DISCONNECTED, p)

    def send_notify_deployment_ready(self) -> None:
        self.send(MsgType.NOTIFY_DEPLOYMENT_READY, Packet())

    def send_cluster_heartbeat(self) -> None:
        """Cluster-link liveness probe (game/gate↔dispatcher, both
        directions); consumed at the recv seam, never routed."""
        self.send(MsgType.HEARTBEAT, Packet())

    def send_start_freeze_game(self) -> None:
        self.send(MsgType.START_FREEZE_GAME, Packet())

    def send_start_freeze_game_ack(self) -> None:
        self.send(MsgType.START_FREEZE_GAME_ACK, Packet())

    def send_kvreg_register(self, key: str, value: str, force: bool) -> None:
        p = Packet()
        p.append_varstr(key)
        p.append_varstr(value)
        p.append_bool(force)
        self.send(MsgType.KVREG_REGISTER, p)

    def send_game_lbc_info(self, cpu_percent: float) -> None:
        p = Packet()
        p.append_float32(cpu_percent)
        self.send(MsgType.GAME_LBC_INFO, p)

    def send_game_load_report(self, report: dict) -> None:
        """Rich per-game load report (rebalance/report.py schema): cpu%,
        entities, tick p95, queue depth, per-space populations. Feeds the
        LBC heap AND the dispatcher-side rebalancer."""
        p = Packet()
        p.append_data(report)
        self.send(MsgType.GAME_LOAD_REPORT, p)

    def send_rebalance_migrate(
        self, from_space: str, to_space: str, to_game: int, count: int
    ) -> None:
        """Dispatcher→game rebalance command: the receiving (donor) game
        selects up to ``count`` movable entities in ``from_space`` and
        drives each through the hardened migrate path into ``to_space``
        on ``to_game`` (rebalance/migrator.py)."""
        p = Packet()
        p.append_entity_id(from_space)
        p.append_entity_id(to_space)
        p.append_uint16(to_game)
        p.append_uint16(count)
        self.send(MsgType.REBALANCE_MIGRATE, p)

    # --- whole-space migration (v7, ISSUE 18) ------------------------------

    def send_space_migrate_prepare(
        self, spaceid: str, to_game: int, member_eids: list
    ) -> None:
        """Donor game → EVERY dispatcher: the space froze; park the
        LISTED member streams you own, then ack on this same link so the
        ack fences all traffic you forwarded before parking.  The list
        is the freeze-time membership — a member that already migrated
        out must NOT be parked (modelcheck space_member_race)."""
        p = Packet()
        p.append_entity_id(spaceid)
        p.append_uint16(to_game)
        p.append_data(member_eids)
        self.send(MsgType.SPACE_MIGRATE_PREPARE, p)

    def send_space_migrate_prepare_ack(
        self, spaceid: str, dispatcherid: int
    ) -> None:
        p = Packet()
        p.append_entity_id(spaceid)
        p.append_uint16(dispatcherid)
        self.send(MsgType.SPACE_MIGRATE_PREPARE_ACK, p)

    def send_space_migrate_data(
        self, spaceid: str, target_game: int, space_data: dict,
        source_game: int = 0
    ) -> None:
        """The whole-space snapshot (space + members + queued joins),
        routed by the space-owner dispatcher exactly like REAL_MIGRATE.
        ``source_game`` rides as a TRAILING u16 for the same reason as
        REAL_MIGRATE's: a sweep-time bounce-home happens long after the
        forwarding proxy is gone, and the packet is the space's only
        copy."""
        p = Packet()
        p.append_entity_id(spaceid)
        p.append_uint16(target_game)
        p.append_data(space_data)
        p.append_uint16(source_game)
        self.send(MsgType.SPACE_MIGRATE_DATA, p)

    def send_space_migrate_abort(self, spaceid: str, reason: str) -> None:
        """Either direction: dispatcher→donor (target dead at PREPARE
        time) or donor→dispatchers (deadline fired — unpark the members;
        the donor has already unfrozen in place)."""
        p = Packet()
        p.append_entity_id(spaceid)
        p.append_varstr(reason)
        self.send(MsgType.SPACE_MIGRATE_ABORT, p)

    def send_space_migrate_ack(self, spaceid: str, gameid: int) -> None:
        """Receiver game → space-owner dispatcher: restore completed
        (closes the dispatcher's handoff telemetry entry; member
        routing rides each NOTIFY_CREATE_ENTITY, not this ack)."""
        p = Packet()
        p.append_entity_id(spaceid)
        p.append_uint16(gameid)
        self.send(MsgType.SPACE_MIGRATE_ACK, p)

    def send_rebalance_migrate_space(
        self, spaceid: str, to_game: int
    ) -> None:
        """Dispatcher→game: move the WHOLE space (members, slab columns,
        interest edges) to ``to_game`` via the two-phase handoff
        (rebalance/migrator.py space states)."""
        p = Packet()
        p.append_entity_id(spaceid)
        p.append_uint16(to_game)
        self.send(MsgType.REBALANCE_MIGRATE_SPACE, p)

    def send_rebalance_plan(self, plan: dict) -> None:
        """Planner-service game → its owner dispatcher: a rebalance plan
        computed on the service plane (planner failover, ISSUE 18); the
        dispatcher validates liveness and dispatches the commands."""
        p = Packet()
        p.append_data(plan)
        self.send(MsgType.REBALANCE_PLAN, p)

    # --- redirect range: game → client via gate ----------------------------
    # Payloads start with [u16 gateid][clientid]; the dispatcher routes on the
    # gateid (DispatcherService.go:841-844) and the gate strips the prefix
    # before forwarding to the client (GateService.go:262-293).

    def _client_packet(self, gateid: int, clientid: str) -> Packet:
        p = Packet()
        p.append_uint16(gateid)
        p.append_client_id(clientid)
        return p

    def send_create_entity_on_client(
        self,
        gateid: int,
        clientid: str,
        is_player: bool,
        eid: str,
        typename: str,
        client_attrs: dict,
        x: float,
        y: float,
        z: float,
        yaw: float,
    ) -> None:
        p = self._client_packet(gateid, clientid)
        p.append_bool(is_player)
        p.append_entity_id(eid)
        p.append_varstr(typename)
        p.append_data(client_attrs)
        p.append_float32(x).append_float32(y).append_float32(z).append_float32(yaw)
        self.send(MsgType.CREATE_ENTITY_ON_CLIENT, p)

    def send_destroy_entity_on_client(
        self, gateid: int, clientid: str, typename: str, eid: str
    ) -> None:
        p = self._client_packet(gateid, clientid)
        p.append_varstr(typename)
        p.append_entity_id(eid)
        self.send(MsgType.DESTROY_ENTITY_ON_CLIENT, p)

    def send_notify_map_attr_change_on_client(
        self, gateid: int, clientid: str, eid: str, path: list, key: str,
        val: object,
    ) -> None:
        p = self._client_packet(gateid, clientid)
        p.append_entity_id(eid)
        p.append_data(path)
        p.append_varstr(key)
        p.append_data(val)
        self.send(MsgType.NOTIFY_MAP_ATTR_CHANGE_ON_CLIENT, p)

    def send_notify_map_attr_del_on_client(
        self, gateid: int, clientid: str, eid: str, path: list, key: str
    ) -> None:
        p = self._client_packet(gateid, clientid)
        p.append_entity_id(eid)
        p.append_data(path)
        p.append_varstr(key)
        self.send(MsgType.NOTIFY_MAP_ATTR_DEL_ON_CLIENT, p)

    def send_notify_map_attr_clear_on_client(
        self, gateid: int, clientid: str, eid: str, path: list
    ) -> None:
        p = self._client_packet(gateid, clientid)
        p.append_entity_id(eid)
        p.append_data(path)
        self.send(MsgType.NOTIFY_MAP_ATTR_CLEAR_ON_CLIENT, p)

    def send_notify_list_attr_change_on_client(
        self, gateid: int, clientid: str, eid: str, path: list, index: int,
        val: object,
    ) -> None:
        p = self._client_packet(gateid, clientid)
        p.append_entity_id(eid)
        p.append_data(path)
        p.append_uint32(index)
        p.append_data(val)
        self.send(MsgType.NOTIFY_LIST_ATTR_CHANGE_ON_CLIENT, p)

    def send_notify_list_attr_pop_on_client(
        self, gateid: int, clientid: str, eid: str, path: list
    ) -> None:
        p = self._client_packet(gateid, clientid)
        p.append_entity_id(eid)
        p.append_data(path)
        self.send(MsgType.NOTIFY_LIST_ATTR_POP_ON_CLIENT, p)

    def send_notify_list_attr_append_on_client(
        self, gateid: int, clientid: str, eid: str, path: list, val: object
    ) -> None:
        p = self._client_packet(gateid, clientid)
        p.append_entity_id(eid)
        p.append_data(path)
        p.append_data(val)
        self.send(MsgType.NOTIFY_LIST_ATTR_APPEND_ON_CLIENT, p)

    def send_call_entity_method_on_client(
        self, gateid: int, clientid: str, eid: str, method: str, args: tuple
    ) -> None:
        p = self._client_packet(gateid, clientid)
        p.append_entity_id(eid)
        p.append_varstr(method)
        p.append_args(args)
        self.send(MsgType.CALL_ENTITY_METHOD_ON_CLIENT, p)

    def send_set_clientproxy_filter_prop(
        self, gateid: int, clientid: str, key: str, val: str
    ) -> None:
        p = self._client_packet(gateid, clientid)
        p.append_varstr(key)
        p.append_varstr(val)
        self.send(MsgType.SET_CLIENTPROXY_FILTER_PROP, p)

    def send_clear_clientproxy_filter_props(self, gateid: int, clientid: str) -> None:
        p = self._client_packet(gateid, clientid)
        self.send(MsgType.CLEAR_CLIENTPROXY_FILTER_PROPS, p)

    # --- gate-handled broadcast --------------------------------------------

    def send_call_filtered_client_proxies(
        self, op: FilterOp, key: str, val: str, method: str, args: tuple
    ) -> None:
        """Broadcast an RPC to every client whose filter prop ``key`` compares
        to ``val`` under ``op`` (gate FilterTree, GateService.go / FilterTree.go)."""
        p = Packet()
        p.append_byte(int(op))
        p.append_varstr(key)
        p.append_varstr(val)
        p.append_varstr(method)
        p.append_args(args)
        self.send(MsgType.CALL_FILTERED_CLIENTS, p)

    # --- client → gate -----------------------------------------------------

    def send_heartbeat(self) -> None:
        self.send(MsgType.HEARTBEAT_FROM_CLIENT, Packet())

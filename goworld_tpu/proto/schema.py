"""Declarative wire schema: the single source of truth for payload layouts.

Every ``MsgType`` gets ONE field-sequence spec here.  The typed senders in
``proto/conn.py`` and the handler-side reads in ``dispatcher/``, ``gate/``,
``game/`` and ``rebalance/`` are checked against these specs by gwlint's
R7 proto-conformance rule (analysis/rules.py), which ALSO pins a digest of
the whole table against ``SCHEMA_HISTORY`` below — so a layout edit that
forgets to bump ``PROTO_VERSION`` fails the lint instead of mis-framing a
mixed-version cluster (the SET_GATE_ID fresh-before-version footgun,
msgtypes.py:33-39, is now machine-checked).

Field kinds map 1:1 onto the Packet codec (netutil/packet.py):

========  ==========================  ======================
kind      append primitive            read primitive
========  ==========================  ======================
u8        append_byte                 read_byte
bool      append_bool                 read_bool
u16       append_uint16               read_uint16
u32       append_uint32               read_uint32
u64       append_uint64               read_uint64
f32       append_float32              read_float32
f64       append_float64              read_float64
eid       append_entity_id            read_entity_id
cid       append_client_id            read_client_id
varstr    append_varstr               read_varstr
varbytes  append_varbytes             read_varbytes
data      append_data (msgpack)       read_data
args      append_args                 read_args
========  ==========================  ======================

Structural rules the table encodes (validated at import):

- every msgtype in the redirect range (1001..1499) starts with the
  ``[u16 gateid][cid clientid]`` prefix the dispatcher routes on and the
  gate strips (msgtypes.py:8-9);
- ``raw`` names a trailing region of raw bytes after the declared fields
  (the fixed-record sync payloads, proto/conn.py SYNC_DTYPE /
  CLIENT_SYNC_DTYPE) — senders build it wholesale, readers slice it;
- the tracing trailer (v4) is NOT a schema field: a sampled packet sets
  MSGTYPE_TRACE_FLAG and appends TRACE_TRAILER_BYTES after the payload,
  stripped at the recv seam before any handler read — the digest covers
  the rule so changing the trailer size is a layout change too.

Declared-but-in-transit fields: ``gate_appended`` marks a suffix the GATE
appends while forwarding a client-originated packet (today only the
trailing clientid of CALL_ENTITY_METHOD_FROM_CLIENT) — the client's pack
site legitimately stops right before it.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Iterable, Optional, Sequence

from goworld_tpu.netutil.packet import Packet
from goworld_tpu.proto.msgtypes import (
    PROTO_VERSION,
    REDIRECT_MAX,
    REDIRECT_MIN,
    MsgType,
)

#: v4 tracing-trailer size (telemetry/tracing.py TRAILER_SIZE) — declared
#: here as a plain literal so the digest covers it without importing the
#: telemetry stack; test_modelcheck pins it equal to the live constant.
TRACE_TRAILER_BYTES = 17

#: Field kind -> Packet append/read method names.  R7 uses these tables to
#: translate call sites into kind sequences; keep them exhaustive.
KIND_APPEND: dict[str, str] = {
    "u8": "append_byte", "bool": "append_bool", "u16": "append_uint16",
    "u32": "append_uint32", "u64": "append_uint64", "f32": "append_float32",
    "f64": "append_float64", "eid": "append_entity_id",
    "cid": "append_client_id", "varstr": "append_varstr",
    "varbytes": "append_varbytes", "data": "append_data",
    "args": "append_args",
}
KIND_READ: dict[str, str] = {
    "u8": "read_byte", "bool": "read_bool", "u16": "read_uint16",
    "u32": "read_uint32", "u64": "read_uint64", "f32": "read_float32",
    "f64": "read_float64", "eid": "read_entity_id", "cid": "read_client_id",
    "varstr": "read_varstr", "varbytes": "read_varbytes", "data": "read_data",
    "args": "read_args",
}
APPEND_TO_KIND: dict[str, str] = {v: k for k, v in KIND_APPEND.items()}
READ_TO_KIND: dict[str, str] = {v: k for k, v in KIND_READ.items()}

Field = tuple[str, str]  # (field name, kind)

#: The routing prefix of every redirect-range payload
#: (DispatcherService.go:841-844 routes on it; the gate strips it).
REDIRECT_PREFIX: tuple[Field, ...] = (("gateid", "u16"), ("clientid", "cid"))


@dataclasses.dataclass(frozen=True)
class MessageSchema:
    msgtype: MsgType
    fields: tuple[Field, ...]
    #: name of a trailing raw-bytes region after the declared fields
    #: (None = the fields ARE the whole payload).
    raw: Optional[str] = None
    #: number of TRAILING fields appended by the gate in transit (the
    #: originating client's pack site stops before them).
    gate_appended: int = 0

    def kinds(self) -> tuple[str, ...]:
        return tuple(kind for _name, kind in self.fields)


def schema(msgtype: MsgType, *fields: Field, raw: Optional[str] = None,
           gate_appended: int = 0) -> MessageSchema:
    """Declarator — called with literal tuples only, so gwlint R7 can
    re-read the whole table from this module's AST without importing it
    (fixture trees lint the same way the real tree does)."""
    return MessageSchema(msgtype, tuple(fields), raw=raw,
                         gate_appended=gate_appended)


def _redirect(msgtype: MsgType, *fields: Field) -> MessageSchema:
    return schema(msgtype, *REDIRECT_PREFIX, *fields)


SCHEMAS: tuple[MessageSchema, ...] = (
    # --- dispatcher-handled (1..999) ---------------------------------------
    schema(MsgType.SET_GAME_ID,
           ("gameid", "u16"), ("is_reconnect", "bool"),
           ("is_restore", "bool"), ("is_ban_boot_entity", "bool"),
           ("entity_ids", "data"), ("proto_version", "u32")),
    schema(MsgType.SET_GAME_ID_ACK, ("ack", "data")),
    # v5: ``fresh`` BEFORE ``gen``/``proto_version`` — the documented
    # mixed-pair footgun (msgtypes.py:33-39): a v4 reader parses the bool
    # as the version's first byte.  The digest pin mechanizes the bump.
    schema(MsgType.SET_GATE_ID,
           ("gateid", "u16"), ("fresh", "bool"), ("gen", "u32"),
           ("proto_version", "u32")),
    schema(MsgType.NOTIFY_CREATE_ENTITY, ("eid", "eid")),
    schema(MsgType.NOTIFY_DESTROY_ENTITY, ("eid", "eid")),
    # Gate boot generation LAST: the dispatcher's boot-eid peek reads the
    # prefix positionally (dispatcher/service.py).
    schema(MsgType.NOTIFY_CLIENT_CONNECTED,
           ("clientid", "cid"), ("gateid", "u16"), ("boot_eid", "eid"),
           ("gate_gen", "u32")),
    schema(MsgType.NOTIFY_CLIENT_DISCONNECTED,
           ("clientid", "cid"), ("owner_eid", "eid")),
    schema(MsgType.CALL_ENTITY_METHOD,
           ("eid", "eid"), ("method", "varstr"), ("args", "args")),
    # The trailing clientid is appended by the GATE while forwarding the
    # client's packet (gate/service.py _handle_client_packet).
    schema(MsgType.CALL_ENTITY_METHOD_FROM_CLIENT,
           ("eid", "eid"), ("method", "varstr"), ("args", "args"),
           ("clientid", "cid"), gate_appended=1),
    schema(MsgType.QUERY_SPACE_GAMEID_FOR_MIGRATE,
           ("spaceid", "eid"), ("eid", "eid"), ("nonce", "u32")),
    schema(MsgType.QUERY_SPACE_GAMEID_FOR_MIGRATE_ACK,
           ("spaceid", "eid"), ("eid", "eid"), ("gameid", "u16"),
           ("nonce", "u32")),
    schema(MsgType.MIGRATE_REQUEST,
           ("eid", "eid"), ("spaceid", "eid"), ("space_gameid", "u16"),
           ("nonce", "u32")),
    schema(MsgType.MIGRATE_REQUEST_ACK,
           ("eid", "eid"), ("spaceid", "eid"), ("space_gameid", "u16"),
           ("nonce", "u32")),
    # v5: trailing source gameid — readable without parsing the bson body
    # so a sweep-time bounce needs no proxy context (proto/conn.py).
    schema(MsgType.REAL_MIGRATE,
           ("eid", "eid"), ("target_game", "u16"), ("migrate_data", "data"),
           ("source_game", "u16")),
    schema(MsgType.CANCEL_MIGRATE, ("eid", "eid")),
    schema(MsgType.LOAD_ENTITY_SOMEWHERE,
           ("gameid", "u16"), ("typename", "varstr"), ("eid", "eid")),
    schema(MsgType.CREATE_ENTITY_SOMEWHERE,
           ("gameid", "u16"), ("typename", "varstr"), ("eid", "eid"),
           ("attrs", "data")),
    schema(MsgType.CALL_NIL_SPACES,
           ("except_game", "u16"), ("method", "varstr"), ("args", "args")),
    # Concatenated fixed 32 B records: EntityID(16) + x,y,z,yaw float32
    # (proto/conn.py SYNC_DTYPE); built and sliced wholesale.
    schema(MsgType.SYNC_POSITION_YAW_FROM_CLIENT, raw="sync_records"),
    schema(MsgType.NOTIFY_GAME_CONNECTED, ("gameid", "u16")),
    schema(MsgType.NOTIFY_GAME_DISCONNECTED, ("gameid", "u16")),
    # v5: valid_gen != 0 narrows the detach to OTHER gate generations.
    schema(MsgType.NOTIFY_GATE_DISCONNECTED,
           ("gateid", "u16"), ("valid_gen", "u32")),
    schema(MsgType.NOTIFY_DEPLOYMENT_READY),
    schema(MsgType.START_FREEZE_GAME),
    schema(MsgType.START_FREEZE_GAME_ACK),
    schema(MsgType.KVREG_REGISTER,
           ("key", "varstr"), ("value", "varstr"), ("force", "bool")),
    schema(MsgType.GAME_LBC_INFO, ("cpu_percent", "f32")),
    schema(MsgType.HEARTBEAT),
    schema(MsgType.GAME_LOAD_REPORT, ("report", "data")),
    schema(MsgType.REBALANCE_MIGRATE,
           ("from_space", "eid"), ("to_space", "eid"), ("to_game", "u16"),
           ("count", "u16")),
    # v7 whole-space handoff (msgtypes.py:31-37 protocol notes).  The
    # member list rides msgpack: dispatchers park exactly the LISTED
    # eids (the freeze-time membership — a member that already migrated
    # out is not parked; modelcheck space_member_race found the hole).
    schema(MsgType.SPACE_MIGRATE_PREPARE,
           ("spaceid", "eid"), ("to_game", "u16"),
           ("member_eids", "data")),
    schema(MsgType.SPACE_MIGRATE_PREPARE_ACK,
           ("spaceid", "eid"), ("dispatcherid", "u16")),
    # Mirrors REAL_MIGRATE: trailing source gameid readable without
    # parsing the bson body, so the sweep-time bounce-home needs no
    # proxy context.
    schema(MsgType.SPACE_MIGRATE_DATA,
           ("spaceid", "eid"), ("target_game", "u16"),
           ("space_data", "data"), ("source_game", "u16")),
    schema(MsgType.SPACE_MIGRATE_ABORT,
           ("spaceid", "eid"), ("reason", "varstr")),
    schema(MsgType.SPACE_MIGRATE_ACK,
           ("spaceid", "eid"), ("gameid", "u16")),
    schema(MsgType.REBALANCE_MIGRATE_SPACE,
           ("spaceid", "eid"), ("to_game", "u16")),
    schema(MsgType.REBALANCE_PLAN, ("plan", "data")),
    # --- redirect range (1001..1499): [u16 gateid][clientid] prefix --------
    _redirect(MsgType.CREATE_ENTITY_ON_CLIENT,
              ("is_player", "bool"), ("eid", "eid"), ("typename", "varstr"),
              ("client_attrs", "data"), ("x", "f32"), ("y", "f32"),
              ("z", "f32"), ("yaw", "f32")),
    _redirect(MsgType.DESTROY_ENTITY_ON_CLIENT,
              ("typename", "varstr"), ("eid", "eid")),
    _redirect(MsgType.NOTIFY_MAP_ATTR_CHANGE_ON_CLIENT,
              ("eid", "eid"), ("path", "data"), ("key", "varstr"),
              ("val", "data")),
    _redirect(MsgType.NOTIFY_MAP_ATTR_DEL_ON_CLIENT,
              ("eid", "eid"), ("path", "data"), ("key", "varstr")),
    _redirect(MsgType.NOTIFY_MAP_ATTR_CLEAR_ON_CLIENT,
              ("eid", "eid"), ("path", "data")),
    _redirect(MsgType.NOTIFY_LIST_ATTR_CHANGE_ON_CLIENT,
              ("eid", "eid"), ("path", "data"), ("index", "u32"),
              ("val", "data")),
    _redirect(MsgType.NOTIFY_LIST_ATTR_POP_ON_CLIENT,
              ("eid", "eid"), ("path", "data")),
    _redirect(MsgType.NOTIFY_LIST_ATTR_APPEND_ON_CLIENT,
              ("eid", "eid"), ("path", "data"), ("val", "data")),
    _redirect(MsgType.CALL_ENTITY_METHOD_ON_CLIENT,
              ("eid", "eid"), ("method", "varstr"), ("args", "args")),
    _redirect(MsgType.SET_CLIENTPROXY_FILTER_PROP,
              ("key", "varstr"), ("val", "varstr")),
    _redirect(MsgType.CLEAR_CLIENTPROXY_FILTER_PROPS),
    # --- gate-handled (1501..1999) -----------------------------------------
    schema(MsgType.CALL_FILTERED_CLIENTS,
           ("op", "u8"), ("key", "varstr"), ("val", "varstr"),
           ("method", "varstr"), ("args", "args")),
    # [u16 gateid] + concatenated [clientid(16) + 32 B record] blocks
    # (proto/conn.py CLIENT_SYNC_DTYPE).
    schema(MsgType.SYNC_POSITION_YAW_ON_CLIENTS,
           ("gateid", "u16"), raw="client_sync_blocks"),
    # v6: [u16 gateid][u8 quantize_bits] + concatenated [clientid(16) +
    # 24 B delta record] blocks (proto/conn.py CLIENT_DELTA_SYNC_DTYPE).
    # The quantize step (2^-quantize_bits world units) rides the payload
    # so the gate/client decode needs no config coupling with the game.
    schema(MsgType.SYNC_POSITION_YAW_DELTA_ON_CLIENTS,
           ("gateid", "u16"), ("quantize_bits", "u8"),
           raw="client_delta_sync_blocks"),
    # --- gate<->client (2001..) --------------------------------------------
    schema(MsgType.HEARTBEAT_FROM_CLIENT),
)

SCHEMAS_BY_TYPE: dict[int, MessageSchema] = {
    int(s.msgtype): s for s in SCHEMAS
}


# --- digest pinning ----------------------------------------------------------


def canonical_lines(
    version: int,
    entries: Iterable[tuple[str, int, Sequence[str], Optional[str]]],
    trailer_bytes: int = TRACE_TRAILER_BYTES,
) -> list[str]:
    """Canonical rendering of a schema table: one line per msgtype in
    value order plus a header carrying the version and the trace-trailer
    rule.  Shared by the runtime digest below and R7's AST-extracted
    digest (analysis/rules.py) so the two can never diverge in format.
    ``entries`` = (msgtype name, value, kind sequence, raw-region name)."""
    lines = [f"proto_version={version};trace_trailer={trailer_bytes}"]
    for name, value, kinds, raw in sorted(entries, key=lambda e: e[1]):
        body = ",".join(kinds)
        if raw is not None:
            body = f"{body}+raw:{raw}" if body else f"raw:{raw}"
        lines.append(f"{value}:{name}={body}")
    return lines


def digest_of(
    version: int,
    entries: Iterable[tuple[str, int, Sequence[str], Optional[str]]],
    trailer_bytes: int = TRACE_TRAILER_BYTES,
) -> str:
    text = "\n".join(canonical_lines(version, entries, trailer_bytes))
    return hashlib.sha256(text.encode("utf-8")).hexdigest()[:16]


def schema_digest() -> str:
    """Digest of the table above under the CURRENT PROTO_VERSION."""
    return digest_of(
        PROTO_VERSION,
        [(s.msgtype.name, int(s.msgtype), s.kinds(), s.raw)
         for s in SCHEMAS])


#: Append-only version -> digest pin.  gwlint R7 fails when the computed
#: digest differs from this table's entry for the CURRENT PROTO_VERSION —
#: i.e. any layout change must land as a new (version, digest) pair, with
#: the PROTO_VERSION bump in msgtypes.py, in the same commit.  Earlier
#: entries stay forever: deleting or rewriting one is visible in review
#: and means the mixed-version handshake guard no longer matches history.
SCHEMA_HISTORY: dict[int, str] = {
    5: "6707328a4b365972",
    6: "3f2d7dd284f1af13",
    7: "08a4c48960727504",
}


# --- structural validation (runs at import; cheap tuple scans) ---------------


def validate() -> None:
    seen: set[int] = set()
    for s in SCHEMAS:
        v = int(s.msgtype)
        if v in seen:
            raise AssertionError(f"duplicate schema for {s.msgtype!r}")
        seen.add(v)
        for _name, kind in s.fields:
            if kind not in KIND_APPEND:
                raise AssertionError(
                    f"{s.msgtype.name}: unknown field kind {kind!r}")
        if REDIRECT_MIN <= v <= REDIRECT_MAX:
            if s.fields[:2] != REDIRECT_PREFIX:
                raise AssertionError(
                    f"{s.msgtype.name} is in the redirect range but does "
                    f"not start with the [u16 gateid][clientid] prefix")
        if s.gate_appended and not s.fields:
            raise AssertionError(
                f"{s.msgtype.name}: gate_appended without fields")
    missing = [t for t in MsgType if int(t) not in seen]
    if missing:
        raise AssertionError(
            f"msgtypes without a wire schema: {[t.name for t in missing]} "
            f"— declare the layout here before adding the type")


validate()


# --- example payloads (schema-driven fuzz + tests) ---------------------------

_EXAMPLE_EID = "E" * 16  # ENTITYID_LENGTH (common/entity_id.py)

#: Per-kind example values.  ``data`` defaults to a dict because most
#: bson-ish fields carry mappings; per-field overrides below fix the rest.
_KIND_EXAMPLES: dict[str, object] = {
    "u8": 3, "bool": True, "u16": 7, "u32": 99, "u64": 1 << 40,
    "f32": 1.5, "f64": 2.5, "eid": _EXAMPLE_EID, "cid": _EXAMPLE_EID,
    "varstr": "method_name", "varbytes": b"\x01\x02", "data": {"k": 1},
    "args": ("a", 2),
}

#: (msgtype, field name) -> example value, where the kind default would
#: not satisfy the handler's structural expectations.
_FIELD_EXAMPLES: dict[tuple[int, str], object] = {
    (int(MsgType.SET_GAME_ID), "entity_ids"): [_EXAMPLE_EID],
    (int(MsgType.SET_GAME_ID), "proto_version"): PROTO_VERSION,
    (int(MsgType.SET_GATE_ID), "proto_version"): PROTO_VERSION,
    (int(MsgType.SET_GAME_ID_ACK), "ack"): {
        "online_games": [1], "rejected": [], "kvreg": {}, "ready": True},
    (int(MsgType.GAME_LOAD_REPORT), "report"): {
        "cpu": 1.0, "entities": 1, "spaces": {}},
    (int(MsgType.SPACE_MIGRATE_PREPARE), "member_eids"): [_EXAMPLE_EID],
    (int(MsgType.SPACE_MIGRATE_DATA), "space_data"): {
        "space": {}, "members": {}},
    (int(MsgType.SPACE_MIGRATE_ABORT), "reason"): "deadline",
    (int(MsgType.REBALANCE_PLAN), "plan"): {
        "moves": [], "space_moves": []},
    (int(MsgType.NOTIFY_MAP_ATTR_CHANGE_ON_CLIENT), "path"): [],
    (int(MsgType.NOTIFY_MAP_ATTR_DEL_ON_CLIENT), "path"): [],
    (int(MsgType.NOTIFY_MAP_ATTR_CLEAR_ON_CLIENT), "path"): [],
    (int(MsgType.NOTIFY_LIST_ATTR_CHANGE_ON_CLIENT), "path"): [],
    (int(MsgType.NOTIFY_LIST_ATTR_POP_ON_CLIENT), "path"): [],
    (int(MsgType.NOTIFY_LIST_ATTR_APPEND_ON_CLIENT), "path"): [],
}

#: Example raw-region payloads (one sync record / one client block).
_RAW_EXAMPLES: dict[str, bytes] = {
    "sync_records": b"",  # filled lazily to avoid an import cycle
    "client_sync_blocks": b"",
    "client_delta_sync_blocks": b"",
}


def _raw_example(region: str) -> bytes:
    from goworld_tpu.proto.conn import (
        pack_client_delta_sync_blocks,
        pack_client_sync_blocks,
        pack_sync_record,
    )

    if region == "sync_records":
        return pack_sync_record(_EXAMPLE_EID, 1.0, 2.0, 3.0, 0.5)
    if region == "client_sync_blocks":
        return pack_client_sync_blocks(
            [(_EXAMPLE_EID, _EXAMPLE_EID, 1.0, 2.0, 3.0, 0.5)])
    if region == "client_delta_sync_blocks":
        return pack_client_delta_sync_blocks(
            [(_EXAMPLE_EID, _EXAMPLE_EID, 1, -2, 3, 0)])
    raise KeyError(region)


def example_packet(msgtype: int) -> Packet:
    """A structurally valid payload for ``msgtype`` built strictly from
    its schema — the seed the truncation/mutation fuzz cuts up."""
    s = SCHEMAS_BY_TYPE[int(msgtype)]
    p = Packet()
    for name, kind in s.fields:
        value = _FIELD_EXAMPLES.get((int(s.msgtype), name),
                                    _KIND_EXAMPLES[kind])
        getattr(p, KIND_APPEND[kind])(value)
    if s.raw is not None:
        p.append_bytes(_raw_example(s.raw))
    return p


def read_fields(packet: Packet, msgtype: int) -> dict[str, object]:
    """Read a payload field-by-field per its schema (tests + the v4/v5
    mis-framing demo).  Raises ValueError on truncation like every other
    parser (netutil/packet.py PacketReadError)."""
    s = SCHEMAS_BY_TYPE[int(msgtype)]
    out: dict[str, object] = {}
    for name, kind in s.fields:
        out[name] = getattr(packet, KIND_READ[kind])()
    if s.raw is not None:
        out[s.raw] = packet.read_rest()
    return out

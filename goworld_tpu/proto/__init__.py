"""Message protocol: the wire ABI between game / gate / dispatcher / client.

Reference parity: ``engine/proto`` — MsgType ranges (proto.go:19-133):
1..999 dispatcher-handled, 1001..1499 redirected by dispatcher to the owning
client's gate, 1501..1999 gate-handled broadcast, 2001+ gate↔client direct.
"""

from goworld_tpu.proto.msgtypes import MsgType, FilterOp
from goworld_tpu.proto.conn import GoWorldConnection, SYNC_RECORD_SIZE

__all__ = ["MsgType", "FilterOp", "GoWorldConnection", "SYNC_RECORD_SIZE"]

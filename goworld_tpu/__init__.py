"""goworld_tpu — a TPU-native distributed game-server framework.

A from-scratch rebuild of the capability surface of GoWorld
(reference: /root/reference, a pure-Go distributed game server engine;
see doc.go:6-13) re-designed TPU-first:

- Control plane: asyncio processes (dispatcher / gate / game) speaking a
  framed msgpack protocol (reference: engine/netutil, engine/proto).
- Compute plane: the per-Space AOI (area-of-interest) hot loop
  (reference: engine/entity/Space.go:211-259 + xiaonanln/go-aoi) runs as
  batched JAX/Pallas spatial-hash neighbor kernels on TPU, with
  jax.sharding/shard_map for multi-chip position all-gather.

The public facade mirrors the reference's ``goworld.go`` (goworld.go:17-256).
"""

__version__ = "0.1.0"


def __getattr__(name: str):
    # Delegate to the lazy facade (goworld.go-style API) without importing
    # any subsystem eagerly. importlib (not ``from goworld_tpu import``) —
    # attribute access on the partially-initialized package would recurse.
    import importlib

    facade = importlib.import_module("goworld_tpu.facade")
    return getattr(facade, name)


def __dir__():
    import importlib

    facade = importlib.import_module("goworld_tpu.facade")
    return sorted(set(globals()) | set(facade.__all__))

"""Runtime utilities: logging, panicless wrappers, post queue, timers,
operation monitoring, crontab and async job groups.

Reference parity: engine/gwlog, engine/gwutils, engine/post, engine/opmon,
engine/crontab, engine/async (see SURVEY.md §2.1).
"""

"""Structured logging for all framework components.

Reference parity: ``engine/gwlog/gwlog.go:16-169`` — zap-based sugar logger
with a per-component ``source`` field, level parsing, ``TraceError`` (error +
stack dump) and Fatal/Panic helpers. Here we build on the stdlib ``logging``
module with the same surface.

``[log] format = json`` switches every handler to one JSON object per line
(level/ts/source/msg) with automatic ``trace_id`` injection when the line
is emitted inside an active distributed-trace span (telemetry/tracing.py) —
so grepping a trace id across the per-process logs of a cluster yields the
exact log lines of one sampled request. The zap-parity text format stays
the default.
"""

from __future__ import annotations

import json
import logging
import sys
import traceback

_FORMAT = "%(asctime)s.%(msecs)03d %(levelname).1s %(source)s %(message)s"
_DATEFMT = "%Y-%m-%d %H:%M:%S"

_source = "goworld"
_logger = logging.getLogger("goworld_tpu")
_configured = False


class _SourceFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        if not hasattr(record, "source"):
            record.source = _source
        return True


class _JsonFormatter(logging.Formatter):
    """One JSON object per line; trace_id injected inside active spans."""

    def format(self, record: logging.LogRecord) -> str:
        obj = {
            "ts": round(record.created, 6),
            "level": record.levelname.lower(),
            "source": getattr(record, "source", _source),
            "msg": record.getMessage(),
        }
        # Lazy import: gwlog must stay importable before telemetry (and
        # tracing itself logs through gwlog).
        try:
            from goworld_tpu.telemetry import tracing

            ctx = tracing.current()
            if ctx is not None:
                obj["trace_id"] = f"{ctx.trace_id:016x}"
        except Exception:
            pass
        if record.exc_info:
            obj["exc"] = self.formatException(record.exc_info)
        return json.dumps(obj, separators=(",", ":"), default=str)


def set_source(source: str) -> None:
    """Set the component tag (e.g. ``game1`` / ``gate2`` / ``dispatcher1``)."""
    global _source
    _source = source


def get_source() -> str:
    """The component tag (process identity for /trace exports)."""
    return _source


def setup(level: str = "info", logfile: str | None = None,
          stderr: bool = True, fmt: str = "text") -> None:
    """Initialise handlers. Mirrors binutil.SetupGWLog (binutil.go:50-82).
    ``fmt``: "text" (zap-parity lines, default) or "json" ([log] format)."""
    global _configured
    if fmt not in ("text", "json"):
        raise ValueError(f"log format must be text|json, got {fmt!r}")
    for h in _logger.handlers:
        h.close()
    _logger.handlers.clear()
    _logger.setLevel(parse_level(level))
    _logger.propagate = False
    handlers: list[logging.Handler] = []
    if logfile:
        handlers.append(logging.FileHandler(logfile))
    if stderr or not handlers:
        handlers.append(logging.StreamHandler(sys.stderr))
    formatter = (_JsonFormatter() if fmt == "json"
                 else logging.Formatter(_FORMAT, _DATEFMT))
    for h in handlers:
        h.setFormatter(formatter)
        h.addFilter(_SourceFilter())
        _logger.addHandler(h)
    _configured = True


def parse_level(level: str) -> int:
    m = {
        "debug": logging.DEBUG,
        "info": logging.INFO,
        "warn": logging.WARNING,
        "warning": logging.WARNING,
        "error": logging.ERROR,
        "panic": logging.CRITICAL,
        "fatal": logging.CRITICAL,
    }
    try:
        return m[level.lower()]
    except KeyError:
        raise ValueError(f"unknown log level: {level!r}")


def _ensure() -> None:
    if not _configured:
        setup()


def debugf(fmt: str, *args) -> None:
    _ensure()
    _logger.debug(fmt, *args)


def infof(fmt: str, *args) -> None:
    _ensure()
    _logger.info(fmt, *args)


def warnf(fmt: str, *args) -> None:
    _ensure()
    _logger.warning(fmt, *args)


def errorf(fmt: str, *args) -> None:
    _ensure()
    _logger.error(fmt, *args)


def trace_error(fmt: str, *args) -> None:
    """Error + stack, like gwlog.TraceError (gwlog.go). Inside an ``except``
    block the active exception traceback is logged; otherwise the call stack."""
    _ensure()
    msg = fmt % args if args else fmt
    if sys.exc_info()[0] is not None:
        _logger.error("%s\n%s", msg, traceback.format_exc())
    else:
        _logger.error("%s\n%s", msg, "".join(traceback.format_stack()))


def panicf(fmt: str, *args) -> None:  # gwlint: keep — reference gwlog API (Panicf)
    _ensure()
    _logger.critical(fmt, *args)
    raise RuntimeError(fmt % args if args else fmt)


def fatalf(fmt: str, *args) -> None:  # gwlint: keep — reference gwlog API (Fatalf)
    _ensure()
    _logger.critical(fmt, *args)
    sys.exit(1)

"""Main-loop deferred callback queue.

Reference parity: ``engine/post/post.go:11-44`` — callbacks registered from
anywhere are drained by ``tick()`` at the end of every main-loop iteration.
Single-threaded logic loops + ``post`` is how the reference designs races away
(SURVEY.md §5.2); we keep the same idiom, with a lock so worker threads
(storage/kvdb backends) may post back into the loop.
"""

from __future__ import annotations

import threading
from typing import Callable

from goworld_tpu.utils import gwutils


class PostQueue:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._callbacks: list[Callable[[], None]] = []

    def post(self, cb: Callable[[], None]) -> None:
        with self._lock:
            self._callbacks.append(cb)

    def tick(self) -> int:
        """Drain all callbacks posted so far (including ones posted while
        draining, matching post.Tick's loop-until-empty). Returns count run."""
        n = 0
        while True:
            with self._lock:
                if not self._callbacks:
                    return n
                batch, self._callbacks = self._callbacks, []
            for cb in batch:
                gwutils.run_panicless(cb)
                n += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._callbacks)


# Module-level default queue, mirroring the reference's package-global.
_default = PostQueue()


def post(cb: Callable[[], None]) -> None:
    _default.post(cb)


def tick() -> int:
    return _default.tick()


def clear() -> None:
    """Test helper: drop pending callbacks."""
    global _default
    _default = PostQueue()

"""Named serial async job groups.

Reference parity: ``engine/async/async.go:32-112`` — each *group* is a named
serial queue (one worker goroutine + channel in the reference; one worker
thread + Queue here). Jobs in a group run strictly in order; their callbacks
are marshalled back to the owning main loop via the post queue, so game logic
never sees concurrency. ``wait_clear`` drains all groups (used at terminate /
freeze, reference async.WaitClear).
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
from typing import Any, Callable

from goworld_tpu.utils import gwlog, post


class _Group:
    def __init__(self, name: str) -> None:
        self.name = name
        self.q: queue.Queue = queue.Queue()
        # pending counts queued + currently-executing jobs; guarded by cond so
        # wait_clear can't observe "drained" between dequeue and execution.
        self.pending = 0
        self.cond = threading.Condition()
        self.thread = threading.Thread(target=self._run, name=f"async-{name}", daemon=True)
        self.thread.start()

    def _run(self) -> None:
        while True:
            item = self.q.get()
            if item is None:
                return
            routine, callback = item
            result, err = None, None
            try:
                result = routine()
            except BaseException as e:  # noqa: BLE001
                err = e
                gwlog.errorf("async %s: job failed: %s\n%s", self.name, e, traceback.format_exc())
            if callback is not None:
                # Bind callback as a default too: the loop rebinds the local
                # on the next iteration before posted lambdas run.
                post.post(lambda r=result, e=err, cb=callback: cb(r, e))
            with self.cond:
                self.pending -= 1
                if self.pending == 0:
                    self.cond.notify_all()

    def submit(self, routine: Callable, callback) -> None:
        with self.cond:
            self.pending += 1
        self.q.put((routine, callback))

    def wait_idle(self, deadline: float) -> bool:
        with self.cond:
            while self.pending > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self.cond.wait(remaining)
        return True


_lock = threading.Lock()
_groups: dict[str, _Group] = {}


def append_job(
    group: str,
    routine: Callable[[], Any],
    callback: Callable[[Any, BaseException | None], None] | None = None,
) -> None:
    """Queue ``routine`` on the named serial group; ``callback(result, error)``
    is posted back to the main loop when it completes."""
    with _lock:
        g = _groups.get(group)
        if g is None:
            g = _groups[group] = _Group(group)
    g.submit(routine, callback)


def wait_clear(timeout: float = 30.0) -> bool:
    """Block until every group has finished all queued jobs (including the
    job currently executing). Callbacks already posted back to the main loop
    are not waited on — the caller must keep ticking post."""
    deadline = time.monotonic() + timeout
    with _lock:
        groups = list(_groups.values())
    return all(g.wait_idle(deadline) for g in groups)

"""Cron-style scheduled callbacks with minute resolution.

Reference parity: ``engine/crontab/crontab.go:11-185`` — register callbacks by
(minute, hour, day, month, dayofweek); a **negative value -N means "every N"**
(e.g. minute=-5 → every 5 minutes); checked once per minute off the main timer.
"""

from __future__ import annotations

import itertools
import time
from typing import Callable

from goworld_tpu.utils import gwutils


class CronHandle:
    __slots__ = ("cron_id", "cancelled")

    def __init__(self, cron_id: int) -> None:
        self.cron_id = cron_id
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class Crontab:
    def __init__(self, now: Callable[[], float] = time.time) -> None:
        self._now = now
        self._entries: dict[int, tuple[int, int, int, int, int, Callable]] = {}
        self._handles: dict[int, CronHandle] = {}
        self._seq = itertools.count()
        self._last_minute = int(self._now() // 60)

    def register(
        self,
        minute: int,
        hour: int,
        day: int,
        month: int,
        dayofweek: int,
        cb: Callable[[], None],
    ) -> CronHandle:
        self._validate(minute, 0, 59)
        self._validate(hour, 0, 23)
        self._validate(day, 1, 31)
        self._validate(month, 1, 12)
        # dayofweek: 0=Sunday like the reference (Go time.Weekday); 7 also
        # accepted as Sunday.
        if dayofweek == 7:
            dayofweek = 0
        self._validate(dayofweek, 0, 6)
        h = CronHandle(next(self._seq))
        self._entries[h.cron_id] = (minute, hour, day, month, dayofweek, cb)
        self._handles[h.cron_id] = h
        return h

    @staticmethod
    def _validate(v: int, lo: int, hi: int) -> None:
        if v >= 0 and not (lo <= v <= hi):
            raise ValueError(f"cron field {v} out of range [{lo},{hi}]")

    @staticmethod
    def _match(spec: int, value: int) -> bool:
        if spec < 0:  # every N
            return value % (-spec) == 0
        return spec == value

    def check(self) -> int:
        """Fire entries whose spec matches any minute since the last check.
        Call from the main loop at >= 1/minute cadence. Returns fires."""
        cur_minute = int(self._now() // 60)
        fired = 0
        while self._last_minute < cur_minute:
            self._last_minute += 1
            t = time.localtime(self._last_minute * 60)
            for cron_id, (mi, h, d, mo, dow, cb) in list(self._entries.items()):
                handle = self._handles.get(cron_id)
                if handle is not None and handle.cancelled:
                    del self._entries[cron_id]
                    del self._handles[cron_id]
                    continue
                # tm_wday is Monday=0; convert to Sunday=0 (Go time.Weekday).
                if (
                    self._match(mi, t.tm_min)
                    and self._match(h, t.tm_hour)
                    and self._match(d, t.tm_mday)
                    and self._match(mo, t.tm_mon)
                    and self._match(dow, (t.tm_wday + 1) % 7)
                ):
                    gwutils.run_panicless(cb)
                    fired += 1
        return fired


_default = Crontab()


def register(minute: int, hour: int, day: int, month: int, dayofweek: int, cb) -> CronHandle:
    return _default.register(minute, hour, day, month, dayofweek, cb)


def check() -> int:
    return _default.check()

"""Operation monitor: named op duration stats + slow-op warnings.

Reference parity: ``engine/opmon/opmon.go:37-118`` — operations are wrapped
with a monitor that records count/total/max duration and warns when an op
exceeds its threshold; a periodic dump prints the table.
"""

from __future__ import annotations

import threading
import time

from goworld_tpu.utils import gwlog


_RING = 512  # per-op sample ring for percentiles (beyond reference parity:
# the BASELINE p99 delivery-latency axis needs live percentiles, not just
# count/avg/max — bounded memory, O(1) record, sort only at dump time)


class _OpStat:
    __slots__ = ("count", "total", "max", "ring", "ring_i")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self.ring: list[float] = []
        self.ring_i = 0

    def record(self, took: float) -> None:
        self.count += 1
        self.total += took
        if took > self.max:
            self.max = took
        if len(self.ring) < _RING:
            self.ring.append(took)
        else:
            self.ring[self.ring_i] = took
            self.ring_i = (self.ring_i + 1) % _RING


_lock = threading.Lock()
_stats: dict[str, _OpStat] = {}


class Operation:
    """Usage: ``op = opmon.Operation("dispatch"); ...; op.finish(0.01)``."""

    __slots__ = ("name", "start")

    def __init__(self, name: str) -> None:
        self.name = name
        self.start = time.monotonic()

    def finish(self, warn_threshold: float = 0.0) -> float:
        took = time.monotonic() - self.start
        with _lock:
            st = _stats.get(self.name)
            if st is None:
                st = _stats[self.name] = _OpStat()
            st.record(took)
        if warn_threshold and took > warn_threshold:
            gwlog.warnf("opmon: operation %s took %.3fs > %.3fs", self.name, took, warn_threshold)
        return took


def dump() -> dict[str, dict[str, float]]:
    with _lock:
        out = {}
        for name, st in _stats.items():
            entry = {
                "count": st.count,
                "avg": st.total / st.count if st.count else 0.0,
                "max": st.max,
            }
            if st.ring:
                s = sorted(st.ring)
                # Nearest-rank percentiles: ceil(q*n)-1, NOT int(q*n) —
                # the latter returns the max (p100) for n in 100..101 and
                # overstates p99 generally.
                entry["p50"] = s[max(0, -(-len(s) * 50 // 100) - 1)]
                entry["p99"] = s[max(0, -(-len(s) * 99 // 100) - 1)]
            out[name] = entry
        return out


def dump_log() -> None:
    for name, st in sorted(dump().items()):
        gwlog.infof(
            "opmon: %-32s count=%-8d avg=%.3fms p50=%.3fms p99=%.3fms "
            "max=%.3fms",
            name, st["count"], st["avg"] * 1000,
            st.get("p50", 0.0) * 1000, st.get("p99", 0.0) * 1000,
            st["max"] * 1000,
        )


def reset() -> None:
    with _lock:
        _stats.clear()

"""Operation monitor: named op duration stats + slow-op warnings.

Reference parity: ``engine/opmon/opmon.go:37-118`` — operations are wrapped
with a monitor that records count/total/max duration and warns when an op
exceeds its threshold; a periodic dump prints the table.
"""

from __future__ import annotations

import threading
import time

from goworld_tpu.utils import gwlog


class _OpStat:
    __slots__ = ("count", "total", "max")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.max = 0.0


_lock = threading.Lock()
_stats: dict[str, _OpStat] = {}


class Operation:
    """Usage: ``op = opmon.Operation("dispatch"); ...; op.finish(0.01)``."""

    __slots__ = ("name", "start")

    def __init__(self, name: str) -> None:
        self.name = name
        self.start = time.monotonic()

    def finish(self, warn_threshold: float = 0.0) -> float:
        took = time.monotonic() - self.start
        with _lock:
            st = _stats.get(self.name)
            if st is None:
                st = _stats[self.name] = _OpStat()
            st.count += 1
            st.total += took
            if took > st.max:
                st.max = took
        if warn_threshold and took > warn_threshold:
            gwlog.warnf("opmon: operation %s took %.3fs > %.3fs", self.name, took, warn_threshold)
        return took


def dump() -> dict[str, dict[str, float]]:
    with _lock:
        out = {}
        for name, st in _stats.items():
            out[name] = {
                "count": st.count,
                "avg": st.total / st.count if st.count else 0.0,
                "max": st.max,
            }
        return out


def dump_log() -> None:
    for name, st in sorted(dump().items()):
        gwlog.infof(
            "opmon: %-32s count=%-8d avg=%.3fms max=%.3fms",
            name, st["count"], st["avg"] * 1000, st["max"] * 1000,
        )


def reset() -> None:
    with _lock:
        _stats.clear()

"""Operation monitor: named op duration stats + slow-op warnings.

Reference parity: ``engine/opmon/opmon.go:37-118`` — operations are wrapped
with a monitor that records count/total/max duration and warns when an op
exceeds its threshold; a periodic dump prints the table.

Since the telemetry subsystem landed this module is a thin SHIM: every
``Operation`` records into the ``op_duration_seconds{op=...}`` histogram
family of :data:`goworld_tpu.telemetry.REGISTRY`, so existing call sites
(gate packet handling, storage saves, aoi.dispatch/deliver/drain) feed the
same registry ``/metrics`` renders — one instrumentation plane, two views.
``dump()`` keeps its legacy shape ({name: {count, avg, max, p50, p99}}) for
``/opmon`` and tests; ``telemetry.snapshot()`` is the superset.
"""

from __future__ import annotations

import time

from goworld_tpu import telemetry
from goworld_tpu.utils import gwlog

_OP_METRIC = "op_duration_seconds"


def _family():
    return telemetry.histogram(
        _OP_METRIC,
        "Named operation durations (opmon shim; op = operation name).",
        labelnames=("op",),
    )


class Operation:
    """Usage: ``op = opmon.Operation("dispatch"); ...; op.finish(0.01)``."""

    __slots__ = ("name", "start")

    def __init__(self, name: str) -> None:
        self.name = name
        self.start = time.monotonic()

    def finish(self, warn_threshold: float = 0.0) -> float:
        took = time.monotonic() - self.start
        _family().labels(self.name).observe(took)
        if warn_threshold and took > warn_threshold:
            gwlog.warnf("opmon: operation %s took %.3fs > %.3fs", self.name, took, warn_threshold)
        return took


def dump() -> dict[str, dict[str, float]]:
    """Legacy opmon table: {op: {count, avg, max, p50, p99}} — percentiles
    from the histogram's bounded sample ring (nearest-rank)."""
    out = {}
    for values, hist in _family().children():
        cnt = hist.count
        out[values[0]] = {
            "count": cnt,
            "avg": hist.sum / cnt if cnt else 0.0,
            "max": hist.max,
            "p50": hist.percentile(0.50),
            "p99": hist.percentile(0.99),
        }
    return out


def dump_log() -> None:  # gwlint: keep — operator-facing opmon shim (reference Dump parity)
    for name, st in sorted(dump().items()):
        gwlog.infof(
            "opmon: %-32s count=%-8d avg=%.3fms p50=%.3fms p99=%.3fms "
            "max=%.3fms",
            name, st["count"], st["avg"] * 1000,
            st.get("p50", 0.0) * 1000, st.get("p99", 0.0) * 1000,
            st["max"] * 1000,
        )


def reset() -> None:
    _family().clear()

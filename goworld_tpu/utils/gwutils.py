"""Panic-resilience idioms.

Reference parity: ``engine/gwutils/gwutils.go:6-42`` — ``RunPanicless`` /
``CatchPanic`` / ``RepeatUntilPanicless`` are the core resilience primitives:
every service loop and user callback in the reference runs inside one so a
panicking entity method cannot take the process down (e.g. GameService.go:73).
"""

from __future__ import annotations

import traceback
from typing import Callable, TypeVar

from goworld_tpu.utils import gwlog

T = TypeVar("T")


def run_panicless(fn: Callable[[], T]) -> bool:
    """Run ``fn``; log-and-swallow any exception. Returns True iff no raise."""
    try:
        fn()
        return True
    except BaseException as e:  # noqa: BLE001 - mirror of recover()
        if isinstance(e, (KeyboardInterrupt, SystemExit)):
            raise
        gwlog.errorf("panic in %s: %s\n%s", fn, e, traceback.format_exc())
        return False


def catch_panic(fn: Callable[[], T]) -> BaseException | None:  # gwlint: keep — reference gwutils API (CatchPanic)
    """Run ``fn``; return the exception it raised, if any."""
    try:
        fn()
        return None
    except BaseException as e:  # noqa: BLE001
        if isinstance(e, (KeyboardInterrupt, SystemExit)):
            raise
        gwlog.errorf("panic in %s: %s\n%s", fn, e, traceback.format_exc())
        return e


def repeat_until_panicless(fn: Callable[[], None]) -> None:
    """Re-run ``fn`` until it completes without raising."""
    while not run_panicless(fn):
        pass

"""Per-process debug HTTP server.

Reference parity: ``engine/binutil/binutil.go:26-47`` — every process embeds
an always-on HTTP server (pprof + expvar) on the config ``http_addr``.
Python-native design: a minimal asyncio HTTP/1.1 responder (no external web
framework in this image) serving:

- ``/healthz``   — one JSON object: process kind/id, uptime, PROTO_VERSION,
  dispatcher link states + last-seen ages, entity/client counts (the
  service registers a provider via :func:`set_health_provider`); ops
  probes and the chaos harness read THIS, not /metrics text
- ``/vars``      — JSON snapshot of gwvar published variables (expvar parity)
- ``/metrics``   — Prometheus text exposition of the telemetry registry
  (tick-phase histograms, AOI stage timings/backlog, queue-depth gauges;
  see goworld_tpu/telemetry)
- ``/trace``     — this process's finished-span ring as Chrome trace-event
  JSON (Perfetto-loadable); ``?raw=1`` returns the raw span list that
  tools/tracecat.py merges across all processes of a deployment
- ``/flight``    — the game loop's slow-tick flight recorder (last N tick
  records + the most recent over-budget dump; telemetry/tracing.py)
- ``/opmon``     — JSON dump of operation monitor stats (opmon.go:37-118;
  now a legacy view over the telemetry op_duration_seconds family)
- ``/snapshot``  — this process's cluster-plane row: the /healthz object
  plus the selected metric families the ClusterCollector aggregates
  (telemetry/collector.py)
- ``/cluster``   — the aggregated whole-deployment view, served ONLY by
  the process hosting the collector (the driver dispatcher); rendered
  live by ``python -m goworld_tpu.tools.gwtop``
- ``/stack``     — all-thread stack dump (the practical subset of pprof)
- ``/profile``   — cProfile the main thread for ?seconds=S; ``&mode=jax``
  instead wraps the window in jax.profiler.trace (the step jits of the
  AOI engine included) and returns the trace directory path

SECURITY: this server is unauthenticated and serves state-changing GETs
(``/heap/start`` toggles ~2x allocation overhead process-wide) and CPU-heavy
probes. ``http_addr`` must stay LOOPBACK-BOUND (127.0.0.1) in production;
reach it remotely through an ssh tunnel, never by binding a public
interface.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time
import traceback
from typing import Callable, Optional

from goworld_tpu.utils import gwlog, gwvar

# /healthz detail provider: the process's service registers a zero-arg
# callable returning a JSON-able dict (kind, id, uptime, link states,
# counts). Module-level because each production process runs exactly one
# service; in-process test clusters get whichever service registered last.
_health_provider: Optional[Callable[[], dict]] = None
_module_t0 = time.monotonic()


def set_health_provider(fn: Callable[[], dict]) -> None:
    global _health_provider
    _health_provider = fn


def clear_health_provider(fn: Callable[[], dict]) -> None:
    """Unregister ``fn`` iff it is still the active provider (a service
    stopping must not wipe a newer service's registration)."""
    global _health_provider
    # == not `is`: bound methods are fresh objects per attribute access,
    # but compare equal for the same function + instance.
    if _health_provider == fn:
        _health_provider = None


def health_snapshot() -> dict:
    """The /healthz object (also embedded in /snapshot rows the cluster
    collector scrapes — telemetry/collector.py)."""
    from goworld_tpu.proto.msgtypes import PROTO_VERSION

    health = {
        "status": "ok",
        "pid": os.getpid(),
        "proto_version": PROTO_VERSION,
        "uptime_s": round(time.monotonic() - _module_t0, 3),
    }
    if _health_provider is not None:
        try:
            health.update(_health_provider())
        except Exception as exc:
            health["status"] = "degraded"
            health["health_provider_error"] = str(exc)
    return health


# /cluster provider: the process hosting a ClusterCollector (the driver
# dispatcher) registers its view() here; every other process 404s with a
# pointer. Module-level for the same one-service-per-process reason as
# the health provider.
_cluster_provider: Optional[Callable[[], dict]] = None


def set_cluster_provider(fn: Callable[[], dict]) -> None:
    global _cluster_provider
    _cluster_provider = fn


def clear_cluster_provider(fn: Callable[[], dict]) -> None:
    global _cluster_provider
    if _cluster_provider == fn:
        _cluster_provider = None


def _dump_stacks() -> str:
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {tid} ---")
        out.extend(traceback.format_stack(frame))
    return "\n".join(out)


class DebugHTTPServer:
    def __init__(self, host: str, port: int) -> None:
        self.host = host
        self.port = port
        self._server: Optional[asyncio.base_events.Server] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._serve, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        gwlog.infof("debug http server on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _serve(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(), timeout=10)
            parts = request.decode(errors="replace").split()
            path = parts[1] if len(parts) >= 2 else "/"
            # Drain headers.
            while True:
                line = await asyncio.wait_for(reader.readline(), timeout=10)
                if line in (b"\r\n", b"\n", b""):
                    break
            route = path.split("?")[0]
            if route == "/profile":
                status, ctype, body = await self._profile(path)
            elif route == "/heap/types":
                status, ctype, body = await self._heap_types()
            else:
                status, ctype, body = self._route(route, self._query(path))
            head = (
                f"HTTP/1.1 {status}\r\nContent-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
            )
            writer.write(head.encode() + body)
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionResetError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    @staticmethod
    def _query(path: str) -> dict[str, str]:
        out: dict[str, str] = {}
        if "?" in path:
            for kv in path.split("?", 1)[1].split("&"):
                k, _, v = kv.partition("=")
                out[k] = v
        return out

    async def _profile(self, path: str) -> tuple[str, str, bytes]:
        """CPU-profile the process for ?seconds=N (pprof's /profile slot):
        cProfile runs on the main thread, so everything the game/gate/
        dispatcher loop does in the window is captured. ``&mode=jax``
        instead wraps the window in ``jax.profiler.trace`` — every step
        jit the AOI engine dispatches during it lands in the on-disk
        trace — and returns the trace directory path (open it with
        TensorBoard's profile plugin or xprof)."""
        q = self._query(path)
        seconds = 5.0
        try:
            seconds = min(60.0, max(0.1, float(q.get("seconds", "5"))))
        except ValueError:
            pass
        if q.get("mode") == "jax":
            return await self._profile_jax(seconds)
        import cProfile
        import io
        import pstats

        pr = cProfile.Profile()
        pr.enable()
        await asyncio.sleep(seconds)
        pr.disable()
        buf = io.StringIO()
        pstats.Stats(pr, stream=buf).sort_stats("cumulative").print_stats(80)
        return "200 OK", "text/plain", buf.getvalue().encode()

    async def _profile_jax(self, seconds: float) -> tuple[str, str, bytes]:
        """On-demand device profiling: jax.profiler.trace around an
        S-second window. Gives the TPU side the same ask-the-running-
        process story the span ring gives the host side."""
        import tempfile

        try:
            import jax
        except Exception as exc:  # pragma: no cover - jax always in image
            return ("500 Internal Server Error", "application/json",
                    json.dumps({"error": f"jax unavailable: {exc}"}).encode())
        trace_dir = tempfile.mkdtemp(prefix="goworld_jax_trace_")
        try:
            jax.profiler.start_trace(trace_dir)
            await asyncio.sleep(seconds)
        finally:
            try:
                jax.profiler.stop_trace()
            except Exception as exc:
                return ("500 Internal Server Error", "application/json",
                        json.dumps({"error": str(exc),
                                    "trace_dir": trace_dir}).encode())
        return ("200 OK", "application/json", json.dumps({
            "trace_dir": trace_dir,
            "seconds": seconds,
            "hint": "tensorboard --logdir <trace_dir> (profile plugin), "
                    "or xprof",
        }).encode())

    async def _heap_types(self) -> tuple[str, str, bytes]:
        """GC census: live instance counts by type (top 40) — tells you
        WHAT is retained where tracemalloc tells you what ALLOCATED. Runs
        gc.collect() + the full gc.get_objects() walk in a THREAD EXECUTOR:
        on a large heap the census takes long enough that running it inline
        would stall the asyncio loop this process serves game/gate traffic
        on (ADVICE r5 #2)."""
        import collections as _c
        import gc as _gc

        def census() -> str:
            _gc.collect()
            counts = _c.Counter(
                type(o).__name__ for o in _gc.get_objects())
            return "\n".join(f"{n:9d}  {t}" for t, n in
                             counts.most_common(40))

        body = await asyncio.get_running_loop().run_in_executor(None, census)
        return "200 OK", "text/plain", body.encode()

    def _route(self, path: str, query: Optional[dict] = None) -> tuple[str, str, bytes]:
        if path == "/healthz":
            return ("200 OK", "application/json",
                    json.dumps(health_snapshot(), default=str).encode())
        if path == "/snapshot":
            # One compact JSON row for the cluster collector: /healthz +
            # the cluster-plane metric families (telemetry/collector.py).
            from goworld_tpu.telemetry import collector

            return ("200 OK", "application/json",
                    json.dumps(collector.build_local_snapshot(),
                               default=str).encode())
        if path == "/cluster":
            if _cluster_provider is None:
                return ("404 Not Found", "application/json",
                        json.dumps({
                            "error": "no collector in this process",
                            "hint": "GET /cluster is served by the driver "
                                    "dispatcher's debug port ([telemetry] "
                                    "cluster_snapshot_interval > 0)",
                        }).encode())
            try:
                view = _cluster_provider()
            except Exception as exc:
                return ("500 Internal Server Error", "application/json",
                        json.dumps({"error": str(exc)}).encode())
            return ("200 OK", "application/json",
                    json.dumps(view, default=str).encode())
        if path == "/trace":
            from goworld_tpu.telemetry import tracing

            if (query or {}).get("raw"):
                body = json.dumps({
                    "process": gwlog.get_source(),
                    "pid": os.getpid(),
                    "spans": tracing.snapshot(),
                })
            else:
                body = json.dumps(
                    tracing.export_chrome(gwlog.get_source()))
            return "200 OK", "application/json", body.encode()
        if path == "/flight":
            from goworld_tpu.telemetry import tracing

            rec = tracing.flight_recorder()
            body = json.dumps(
                rec.snapshot() if rec is not None else
                {"recent": [], "last_slow": None,
                 "note": "no tick loop in this process"},
                default=str)
            return "200 OK", "application/json", body.encode()
        if path == "/history":
            from goworld_tpu.telemetry import history

            w = history.active_writer()
            body = json.dumps(
                w.snapshot() if w is not None else
                {"dir": None,
                 "note": "no history writer in this process "
                         "([telemetry] history_dir unset)"},
                default=str)
            return "200 OK", "application/json", body.encode()
        if path == "/heap/start":
            # Live heap profiling (pprof's /heap slot, via tracemalloc):
            # start tracing, then GET /heap for the top Python growth
            # sites since start. ~2x alloc overhead while on; /heap/stop
            # turns it off.
            import tracemalloc

            tracemalloc.start(12)
            return "200 OK", "text/plain", b"tracemalloc started"
        if path == "/heap/stop":
            import tracemalloc

            tracemalloc.stop()
            return "200 OK", "text/plain", b"tracemalloc stopped"
        if path == "/metrics":
            from goworld_tpu import telemetry

            return ("200 OK", "text/plain; version=0.0.4; charset=utf-8",
                    telemetry.render().encode())
        if path == "/heap":
            import tracemalloc

            if not tracemalloc.is_tracing():
                return ("409 Conflict", "text/plain",
                        b"not tracing; GET /heap/start first")
            snap = tracemalloc.take_snapshot()
            lines = []
            for stat in snap.statistics("traceback")[:25]:
                lines.append(f"{stat.size / 1e6:.2f} MB in "
                             f"{stat.count} blocks")
                lines.extend("    " + ln
                             for ln in stat.traceback.format()[-6:])
            return "200 OK", "text/plain", "\n".join(lines).encode()
        if path == "/vars":
            return ("200 OK", "application/json",
                    json.dumps(gwvar.snapshot(), default=str).encode())
        if path == "/opmon":
            from goworld_tpu.utils import opmon

            return ("200 OK", "application/json",
                    json.dumps(opmon.dump(), default=str).encode())
        if path == "/stack":
            return "200 OK", "text/plain", _dump_stacks().encode()
        return "404 Not Found", "text/plain", b"not found"


async def setup_http_server(http_addr: str) -> Optional[DebugHTTPServer]:
    """Start the debug server if ``http_addr`` ("host:port") is configured
    (binutil.SetupHTTPServer; no-op when unset, like the reference)."""
    if not http_addr:
        return None
    host, _, port = http_addr.rpartition(":")
    srv = DebugHTTPServer(host or "127.0.0.1", int(port))
    await srv.start()
    return srv

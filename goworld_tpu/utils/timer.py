"""Timer service driving entity timers and periodic ticks.

Reference parity: the ``xiaonanln/goTimer`` timer wheel the reference embeds
(Entity.go:392-406 for per-entity timers; GameService.go:171 ``timer.Tick()``
drives them once per 5 ms loop iteration). Python-native design: a heapq-based
priority queue with O(log n) add/cancel and a monotonic-clock ``tick()``.

Timers are *cooperative*: they only fire inside ``tick()``, which the owning
single-threaded loop calls — callbacks therefore never race entity logic,
exactly like the reference.
"""

from __future__ import annotations

import heapq
import itertools
import time
from typing import Callable

from goworld_tpu.utils import gwutils


class TimerHandle:
    __slots__ = ("timer_id", "cancelled")

    def __init__(self, timer_id: int) -> None:
        self.timer_id = timer_id
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class TimerService:
    def __init__(self, now: Callable[[], float] = time.monotonic) -> None:
        self._now = now
        self._heap: list[tuple[float, int, TimerHandle, float, Callable]] = []
        self._seq = itertools.count()

    def add_callback(self, delay: float, cb: Callable[[], None]) -> TimerHandle:
        """One-shot timer."""
        return self._schedule(delay, 0.0, cb)

    def add_timer(self, interval: float, cb: Callable[[], None]) -> TimerHandle:
        """Repeating timer with fixed interval."""
        if interval <= 0:
            raise ValueError("repeat interval must be > 0")
        return self._schedule(interval, interval, cb)

    def _schedule(self, delay: float, repeat: float, cb: Callable) -> TimerHandle:
        h = TimerHandle(next(self._seq))
        heapq.heappush(self._heap, (self._now() + delay, h.timer_id, h, repeat, cb))
        return h

    def tick(self) -> int:
        """Fire all due timers; returns number fired."""
        now = self._now()
        fired = 0
        while self._heap and self._heap[0][0] <= now:
            deadline, tid, handle, repeat, cb = heapq.heappop(self._heap)
            if handle.cancelled:
                continue
            if repeat > 0:
                # Re-arm before running so a slow callback can't skew cadence
                # (and so the callback may cancel its own handle).
                next_deadline = deadline + repeat
                if next_deadline <= now:  # missed ticks: don't burst-fire
                    next_deadline = now + repeat
                heapq.heappush(self._heap, (next_deadline, tid, handle, repeat, cb))
            gwutils.run_panicless(cb)
            fired += 1
        return fired

    def next_deadline(self) -> float | None:
        while self._heap and self._heap[0][2].cancelled:
            heapq.heappop(self._heap)
        return self._heap[0][0] if self._heap else None

    def __len__(self) -> int:
        return sum(1 for item in self._heap if not item[2].cancelled)

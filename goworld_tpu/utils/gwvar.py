"""Process-wide published variables.

Reference parity: ``engine/gwvar/gwvar.go:5-29`` — expvar-backed flags
(IsDeploymentReady) served on the debug HTTP port. Python-native design: a
registry of names → value-or-callable, JSON-serialized by the debug HTTP
server (utils/debug_http.py) at ``/vars``.
"""

from __future__ import annotations

from typing import Any

_vars: dict[str, Any] = {}


def set_var(name: str, value: Any) -> None:
    """Publish a value (or a zero-arg callable evaluated at read time)."""
    _vars[name] = value


def get_var(name: str, default: Any = None) -> Any:  # gwlint: keep — accessor beside set/unset
    v = _vars.get(name, default)
    return v() if callable(v) else v


def unset(name: str) -> None:
    """Remove a published variable (stopped services must not serve stale
    probes or keep themselves alive through closure captures)."""
    _vars.pop(name, None)


def snapshot() -> dict[str, Any]:
    out = {}
    for name, v in _vars.items():
        try:
            out[name] = v() if callable(v) else v
        except Exception as exc:  # a broken probe must not kill /vars
            out[name] = f"<error: {exc}>"
    return out


def clear_for_tests() -> None:
    _vars.clear()


# The one variable the reference always publishes (gwvar.go:27-29).
set_var("IsDeploymentReady", False)

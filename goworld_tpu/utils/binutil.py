"""Process bootstrap helpers.

Reference parity: ``engine/binutil`` — the ``-d`` daemon mode (go-daemon on
unix) plus log/stdio plumbing. The debug HTTP server half of binutil lives
in utils/debug_http.py.
"""

from __future__ import annotations

import os
import sys


def daemonize(logfile: str | None = None) -> None:
    """Detach from the controlling terminal (classic unix double fork).

    stdout/stderr are redirected to ``logfile`` (append) or /dev/null, stdin
    to /dev/null. Call before any event loop or thread is created.
    """
    if not hasattr(os, "fork"):  # non-unix: run in foreground
        return
    if os.fork() > 0:
        os._exit(0)  # first parent: let the shell return
    os.setsid()
    if os.fork() > 0:
        os._exit(0)  # session leader exits: can never reacquire a tty
    sys.stdout.flush()
    sys.stderr.flush()
    devnull_r = os.open(os.devnull, os.O_RDONLY)
    os.dup2(devnull_r, 0)
    if logfile:
        out = os.open(logfile, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    else:
        out = os.open(os.devnull, os.O_WRONLY)
    os.dup2(out, 1)
    os.dup2(out, 2)

"""The six engine-specific gwlint rules.

Each checker takes the parsed package (list[ParsedModule]) plus the repo
root and returns Violations.  All checks are heuristic AST passes tuned to
THIS codebase's idioms; anything they over-report is suppressed in the
committed baseline with a written justification, so precision bugs cost a
review line, never a silent pass.  The rules:

- **R1 jit-hygiene** — whole-program: functions reachable from
  ``jax.jit`` / ``vmap`` / ``shard_map`` / ``lax.scan``-style callsites
  (cross-module call graph, ``self.*`` methods resolved) must not call
  host-sync primitives (``.item()``, ``float()`` on non-constants,
  ``np.asarray/np.array``, ``jax.device_get``, ``block_until_ready``) or
  mutate module-level state under trace.
- **R2 hot-path shape** — functions on the tick/collect/route/demux hot
  paths (``@hot_path``-decorated or listed in ``HOT_PATHS``) must not
  contain per-item Python loops over non-constant iterables or
  per-record ``struct.pack`` inside a loop.
- **R3 parse-bounds** — in ``netutil/`` and ``proto/``, unpack/index
  reads of received buffers must be dominated by a ``len()`` guard or an
  enclosing try/except that catches the short-read error.
- **R4 lock discipline** — lock acquisition goes through ``with``; no
  ``time.sleep`` / socket send/recv / blocking queue op lexically inside
  a held-lock region.
- **R5 telemetry hygiene** — metric families register at module scope,
  counters never ``.dec()``, trace spans are context-managed (or
  explicitly recorded) rather than half-entered.
- **R6 config-key drift** — every ini key read in
  ``config/read_config.py`` exists in ``goworld.ini.sample`` and vice
  versa (numbered sections fold into their family; ``start_nodes_N``
  matches the prefix reader).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator, Optional

from goworld_tpu.analysis.core import ParsedModule, Violation

# --- shared AST helpers ------------------------------------------------------


def dotted(node: ast.AST) -> Optional[str]:
    """"a.b.c" for a Name/Attribute chain; None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def module_name(path: str) -> str:
    mod = path[:-3].replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def import_map(mod: ParsedModule) -> dict[str, str]:
    """Local alias -> fully qualified target (relative imports resolved)."""
    modname = module_name(mod.path)
    package = modname if mod.path.endswith("__init__.py") else (
        modname.rsplit(".", 1)[0] if "." in modname else "")
    out: dict[str, str] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    out[a.asname] = a.name
                else:
                    out[a.name.split(".")[0]] = a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                parts = package.split(".") if package else []
                if node.level > 1:
                    parts = parts[: len(parts) - (node.level - 1)]
                base = ".".join(parts + ([node.module] if node.module else []))
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{base}.{a.name}" if base else a.name
    return out


def walk_scoped(tree: ast.AST) -> Iterator[tuple[str, ast.AST]]:
    """Yield (enclosing dotted scope, node) for every node."""

    def visit(node: ast.AST, scope: str) -> Iterator[tuple[str, ast.AST]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                sub = f"{scope}.{child.name}" if scope else child.name
                yield scope, child
                yield from visit(child, sub)
            else:
                yield scope, child
                yield from visit(child, scope)

    yield from visit(tree, "")


def body_nodes(fn: ast.AST, into_nested: bool = True) -> Iterator[ast.AST]:
    """Every node lexically inside ``fn``'s body (optionally skipping
    nested function/lambda bodies — deferred execution)."""

    def visit(node: ast.AST) -> Iterator[ast.AST]:
        for child in ast.iter_child_nodes(node):
            yield child
            if not into_nested and isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
                continue
            yield from visit(child)

    for stmt in getattr(fn, "body", []):
        yield stmt
        yield from visit(stmt)


# --- R1: jit hygiene ---------------------------------------------------------

# wrapper name -> positions of the traced-function argument(s)
_JIT_WRAPPERS = {
    "jit": (0,), "pjit": (0,), "pmap": (0,), "vmap": (0,),
    "shard_map": (0,), "vmapped_position_tick": (0,),
    "grad": (0,), "value_and_grad": (0,), "remat": (0,), "checkpoint": (0,),
    "scan": (0,), "while_loop": (0, 1), "fori_loop": (2,), "cond": (1, 2),
}
# functions whose function-args run on HOST, not under trace
_HOST_CALLBACK_FUNCS = {"pure_callback", "io_callback", "host_callback",
                        "debug_callback"}
_NUMPY_HOST_FUNCS = {"asarray", "array"}


class _ProgramIndex:
    def __init__(self, modules: list[ParsedModule]) -> None:
        self.modules = {module_name(m.path): m for m in modules}
        # modname -> {qualname: def node}
        self.defs: dict[str, dict[str, ast.AST]] = {}
        self.classes: dict[str, set[str]] = {}
        self.imports: dict[str, dict[str, str]] = {}
        self.np_aliases: dict[str, set[str]] = {}
        for name, m in self.modules.items():
            defs: dict[str, ast.AST] = {}
            classes: set[str] = set()
            for scope, node in walk_scoped(m.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{scope}.{node.name}" if scope else node.name
                    defs[qual] = node
                elif isinstance(node, ast.ClassDef):
                    qual = f"{scope}.{node.name}" if scope else node.name
                    classes.add(qual)
            self.defs[name] = defs
            self.classes[name] = classes
            imp = import_map(m)
            self.imports[name] = imp
            self.np_aliases[name] = {
                a for a, tgt in imp.items()
                if tgt == "numpy" or tgt.startswith("numpy.")}

    def resolve(self, modname: str, scope: str,
                ref: str) -> Optional[tuple[str, str]]:
        """Resolve a dotted reference at ``scope`` in ``modname`` to a
        package function: (modname, qualname), or None."""
        defs = self.defs.get(modname, {})
        parts = ref.split(".")
        if parts[0] in ("self", "cls") and len(parts) == 2:
            # method of the nearest enclosing class in the scope chain
            sp = scope.split(".")
            for i in range(len(sp), 0, -1):
                cand = ".".join(sp[:i])
                if cand in self.classes.get(modname, ()):
                    qual = f"{cand}.{parts[1]}"
                    if qual in defs:
                        return (modname, qual)
            return None
        if len(parts) == 1:
            # lexical scope chain, innermost first
            sp = scope.split(".") if scope else []
            for i in range(len(sp), -1, -1):
                cand = ".".join(sp[:i] + [ref]) if i else ref
                if cand in defs:
                    return (modname, cand)
            tgt = self.imports.get(modname, {}).get(ref)
            if tgt and tgt.startswith("goworld_tpu"):
                if "." in tgt:
                    tmod, tname = tgt.rsplit(".", 1)
                    if tname in self.defs.get(tmod, {}):
                        return (tmod, tname)
            return None
        # alias.func: alias must name a package module
        tgt = self.imports.get(modname, {}).get(parts[0])
        if tgt and tgt.startswith("goworld_tpu") and len(parts) == 2:
            if parts[1] in self.defs.get(tgt, {}):
                return (tgt, parts[1])
        return None


def _unwrap_partial(arg: ast.AST) -> ast.AST:
    if isinstance(arg, ast.Call):
        inner = dotted(arg.func)
        if inner and inner.split(".")[-1] == "partial" and arg.args:
            return arg.args[0]
    return arg


def _resolve_traced_arg(index: _ProgramIndex, modname: str, scope: str,
                        arg: ast.AST) -> Optional[tuple[str, str]]:
    """Resolve the function argument of a jit-wrapper call, chasing one
    level of `body = functools.partial(f, ...)` local binding."""
    arg = _unwrap_partial(arg)
    ref = dotted(arg)
    if not ref:
        return None
    hit = index.resolve(modname, scope, ref)
    if hit:
        return hit
    # local variable: find its binding assignment in the enclosing def
    encl = index.defs.get(modname, {}).get(scope)
    if encl is not None and "." not in ref:
        for node in body_nodes(encl):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == ref
                    for t in node.targets):
                src = _unwrap_partial(node.value)
                ref2 = dotted(src)
                if ref2 and ref2 != ref:
                    hit = index.resolve(modname, scope, ref2)
                    if hit:
                        return hit
    return None


def _jit_roots(index: _ProgramIndex) -> set[tuple[str, str]]:
    roots: set[tuple[str, str]] = set()
    for modname, mod in index.modules.items():
        for scope, node in walk_scoped(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{scope}.{node.name}" if scope else node.name
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    d = dotted(target)
                    if d and d.split(".")[-1] in _JIT_WRAPPERS:
                        roots.add((modname, qual))
                    elif (isinstance(dec, ast.Call)
                          and d and d.split(".")[-1] == "partial"
                          and dec.args):
                        inner = dotted(dec.args[0])
                        if inner and inner.split(".")[-1] in _JIT_WRAPPERS:
                            roots.add((modname, qual))
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if not d:
                continue
            name = d.split(".")[-1]
            if name not in _JIT_WRAPPERS:
                continue
            for pos in _JIT_WRAPPERS[name]:
                if pos >= len(node.args):
                    continue
                hit = _resolve_traced_arg(
                    index, modname, scope, node.args[pos])
                if hit:
                    roots.add(hit)
    return roots


def _reachable(index: _ProgramIndex,
               roots: set[tuple[str, str]]) -> set[tuple[str, str]]:
    seen = set(roots)
    frontier = list(roots)
    while frontier:
        modname, qual = frontier.pop()
        fn = index.defs[modname].get(qual)
        if fn is None:
            continue
        mod = index.modules[modname]
        scope = qual
        # host-callback args are excluded from reference resolution
        excluded: set[int] = set()
        for node in body_nodes(fn):
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d and d.split(".")[-1] in _HOST_CALLBACK_FUNCS:
                    for a in node.args:
                        for sub in ast.walk(a):
                            excluded.add(id(sub))
        for node in body_nodes(fn):
            if id(node) in excluded:
                continue
            ref = None
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                ref = node.id
            elif isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, ast.Load):
                ref = dotted(node)
            if not ref:
                continue
            hit = index.resolve(modname, scope, ref)
            if hit and hit not in seen:
                seen.add(hit)
                frontier.append(hit)
        del mod
    return seen


def _module_mutables(mod: ParsedModule) -> set[str]:
    """Module-level names bound to obviously-mutable containers."""
    out: set[str] = set()
    for stmt in mod.tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = [t for t in stmt.targets if isinstance(t, ast.Name)]
            value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name) and stmt.value is not None:
            targets = [stmt.target]
            value = stmt.value
        else:
            continue
        mutable = isinstance(value, (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp, ast.SetComp))
        if isinstance(value, ast.Call):
            d = dotted(value.func)
            if d and d.split(".")[-1] in ("list", "dict", "set",
                                          "defaultdict", "deque",
                                          "OrderedDict"):
                mutable = True
        if mutable:
            out.update(t.id for t in targets)
    return out


_MUTATOR_ATTRS = {"append", "extend", "update", "setdefault", "add",
                  "pop", "popitem", "insert", "remove", "clear"}


def check_r1(modules: list[ParsedModule], root: str) -> list[Violation]:
    index = _ProgramIndex(modules)
    reach = _reachable(index, _jit_roots(index))
    out: list[Violation] = []
    by_mod: dict[str, list[str]] = {}
    for modname, qual in reach:
        by_mod.setdefault(modname, []).append(qual)
    for modname, quals in by_mod.items():
        mod = index.modules[modname]
        np_alias = index.np_aliases[modname]
        mutables = _module_mutables(mod)
        for qual in quals:
            fn = index.defs[modname][qual]
            for node in body_nodes(fn):
                if isinstance(node, ast.Global):
                    out.append(mod.violation(
                        "R1", node,
                        "jit-reachable function rebinds module state "
                        "via `global` — side effects under trace run "
                        "once, at trace time"))
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(
                        node, ast.Assign) else [node.target]
                    for t in targets:
                        if isinstance(t, ast.Subscript):
                            base = dotted(t.value)
                            if base in mutables:
                                out.append(mod.violation(
                                    "R1", node,
                                    f"mutates module-level container "
                                    f"{base!r} under trace — runs at "
                                    f"trace time, not per step"))
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item" and not node.args):
                    out.append(mod.violation(
                        "R1", node,
                        ".item() host-syncs the device stream inside a "
                        "jit-reachable function"))
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr == "block_until_ready"):
                    out.append(mod.violation(
                        "R1", node,
                        "block_until_ready() host-syncs inside a "
                        "jit-reachable function"))
                elif d and d.split(".")[-1] == "device_get":
                    out.append(mod.violation(
                        "R1", node,
                        "jax.device_get host-syncs inside a jit-reachable "
                        "function"))
                elif (d and "." in d and d.split(".")[0] in np_alias
                      and d.split(".")[-1] in _NUMPY_HOST_FUNCS):
                    out.append(mod.violation(
                        "R1", node,
                        f"{d}() materializes on host inside a "
                        f"jit-reachable function (traced values would "
                        f"host-sync; use jnp, or hoist to the host side)"))
                elif (d == "float" and len(node.args) == 1
                      and not isinstance(node.args[0], ast.Constant)):
                    out.append(mod.violation(
                        "R1", node,
                        "float(x) on a non-constant host-syncs if x is "
                        "traced"))
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in _MUTATOR_ATTRS):
                    base = dotted(node.func.value)
                    if base in mutables:
                        out.append(mod.violation(
                            "R1", node,
                            f"mutates module-level container {base!r} "
                            f"under trace — runs at trace time, not per "
                            f"step"))
    return out


# --- R2: hot-path shape ------------------------------------------------------

# path -> function names (bare, matched against the tail of the dotted
# symbol).  These are the per-tick collect/route/demux/fan-out paths the
# fanout and pinned floors measure.
HOT_PATHS: dict[str, set[str]] = {
    "goworld_tpu/entity/slabs.py": {
        "collect_sync_selection", "pack_sync", "collect_sync",
        "run_tick_batches", "set_position_yaw",
    },
    "goworld_tpu/dispatcher/service.py": {
        "_handle_sync_position_yaw_from_client", "_send_pending_syncs",
        "_flush_pending_sync", "_route_to_gate",
    },
    "goworld_tpu/gate/service.py": {
        "_handle_sync_on_clients", "_flush_pending_syncs",
    },
    "goworld_tpu/ops/neighbor.py": {
        "neighbor_step", "build_tables", "diff_events",
    },
}


def _is_const_bounded(it: ast.AST) -> bool:
    if isinstance(it, (ast.Tuple, ast.List, ast.Set, ast.Dict, ast.Constant)):
        return True
    if isinstance(it, ast.Call):
        d = dotted(it.func)
        if d in ("range", "enumerate", "reversed", "zip") and all(
                _is_const_bounded(a) or isinstance(a, ast.Constant)
                for a in it.args):
            return True
    return False


def _hot_functions(mod: ParsedModule) -> list[tuple[str, ast.AST]]:
    listed = HOT_PATHS.get(mod.path, set())
    out = []
    for scope, node in walk_scoped(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        qual = f"{scope}.{node.name}" if scope else node.name
        decorated = any(
            (dotted(dec) or "").split(".")[-1] == "hot_path"
            for dec in node.decorator_list)
        if decorated or node.name in listed:
            out.append((qual, node))
    return out


def check_r2(modules: list[ParsedModule], root: str) -> list[Violation]:
    out: list[Violation] = []
    for mod in modules:
        for qual, fn in _hot_functions(mod):
            loop_spans: list[tuple[int, int]] = []
            for node in body_nodes(fn):
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    loop_spans.append(
                        (node.lineno, node.end_lineno or node.lineno))
                    if not _is_const_bounded(node.iter):
                        src = ast.unparse(node.iter)
                        out.append(mod.violation(
                            "R2", node,
                            f"per-item Python loop over {src!r} on a "
                            f"hot path — vectorize or prove the iterable "
                            f"O(gates), not O(entities)"))
                elif isinstance(node, ast.While):
                    loop_spans.append(
                        (node.lineno, node.end_lineno or node.lineno))
                    out.append(mod.violation(
                        "R2", node,
                        "while-loop on a hot path — prove bounded or "
                        "vectorize"))
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.DictComp, ast.GeneratorExp)):
                    for gen in node.generators:
                        if not _is_const_bounded(gen.iter):
                            src = ast.unparse(gen.iter)
                            out.append(mod.violation(
                                "R2", node,
                                f"per-item comprehension over {src!r} on "
                                f"a hot path"))
            for node in body_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if not d or d.split(".")[-1] not in ("pack", "pack_into"):
                    continue
                parts = d.split(".")
                packish = (parts[0] == "struct"
                           or "struct" in parts[-2].lower()
                           if len(parts) > 1 else False)
                if not packish:
                    continue
                in_loop = any(lo < node.lineno <= hi for lo, hi in loop_spans)
                if in_loop:
                    out.append(mod.violation(
                        "R2", node,
                        f"per-record {d} inside a loop on a hot path — "
                        f"build columns and pack once"))
    return out


# --- R3: parse bounds --------------------------------------------------------

_BUF_PARAM_NAMES = {
    "data", "buf", "buff", "buffer", "payload", "raw", "b", "msg", "frame",
    "chunk", "body", "blob", "segment", "seg", "datagram", "wire", "packed",
}
_RECV_FUNCS = {"recv", "recvfrom", "recv_exact", "read", "read_exact",
               "readexactly"}
_SHORT_READ_ERRORS = {"error", "struct", "IndexError", "ValueError",
                      "Exception", "BaseException", "KeyError"}


def _buffer_names(fn: ast.AST) -> set[str]:
    bufs = {a.arg for a in _all_args(fn) if a.arg in _BUF_PARAM_NAMES}
    # propagate through simple assignments (memoryview(data), data[4:], recv)
    changed = True
    while changed:
        changed = False
        for node in body_nodes(fn):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name) or tgt.id in bufs:
                continue
            src_names = names_in(node.value)
            from_recv = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in _RECV_FUNCS
                for n in ast.walk(node.value))
            if (src_names & bufs) or from_recv:
                bufs.add(tgt.id)
                changed = True
    return bufs


def _all_args(fn: ast.AST) -> list[ast.arg]:
    a = fn.args
    return [*a.posonlyargs, *a.args, *a.kwonlyargs]


_GUARD_FN_RE = re.compile(r"(need|check|require|ensure|guard|bounds)",
                          re.IGNORECASE)


def _guard_lines(fn: ast.AST, bufs: set[str]) -> list[int]:
    """Lines where a len() of a buffer name occurs, or where the buffer
    is passed to a bounds-guard helper (``_need(data, off, 8)`` — the
    conventional names are matched by _GUARD_FN_RE)."""
    out = []
    for node in body_nodes(fn):
        if not isinstance(node, ast.Call):
            continue
        if (isinstance(node.func, ast.Name) and node.func.id == "len"
                and node.args and (names_in(node.args[0]) & bufs)):
            out.append(node.lineno)
            continue
        d = dotted(node.func)
        if (d and _GUARD_FN_RE.search(d.split(".")[-1])
                and any(names_in(a) & bufs for a in node.args)):
            out.append(node.lineno)
    return out


def _try_spans(fn: ast.AST) -> list[tuple[int, int]]:
    spans = []
    for node in body_nodes(fn):
        if not isinstance(node, ast.Try):
            continue
        catches = False
        for h in node.handlers:
            if h.type is None:
                catches = True
            else:
                for t in ([h.type.elts] if isinstance(h.type, ast.Tuple)
                          else [[h.type]]):
                    for e in t:
                        d = dotted(e) or ""
                        if d.split(".")[0] in _SHORT_READ_ERRORS or \
                                d.split(".")[-1] in _SHORT_READ_ERRORS:
                            catches = True
        if catches and node.body:
            lo = node.body[0].lineno
            hi = max(s.end_lineno or s.lineno for s in node.body)
            spans.append((lo, hi))
    return spans


def check_r3(modules: list[ParsedModule], root: str) -> list[Violation]:
    out: list[Violation] = []
    for mod in modules:
        if not (mod.path.startswith("goworld_tpu/netutil/")
                or mod.path.startswith("goworld_tpu/proto/")):
            continue
        for scope, node in walk_scoped(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            bufs = _buffer_names(node)
            if not bufs:
                continue
            guards = _guard_lines(node, bufs)
            tries = _try_spans(node)

            def covered(line: int) -> bool:
                # <= : `if len(parts) == 3 and parts[0] ...` guards
                # same-line reads via short-circuit evaluation
                return (any(g <= line for g in guards)
                        or any(lo <= line <= hi for lo, hi in tries))

            for sub in body_nodes(node):
                if isinstance(sub, ast.Call):
                    d = dotted(sub.func)
                    risky = None
                    if d and d.split(".")[-1] in ("unpack", "unpack_from"):
                        if any(names_in(a) & bufs for a in sub.args):
                            risky = f"{d}()"
                    elif d == "int.from_bytes" and sub.args and (
                            names_in(sub.args[0]) & bufs):
                        risky = "int.from_bytes()"
                    if risky and not covered(sub.lineno):
                        out.append(mod.violation(
                            "R3", sub,
                            f"{risky} reads a received buffer "
                            f"({sorted(names_in(sub) & bufs)}) with no "
                            f"dominating len() guard or short-read "
                            f"try/except — a truncated frame crashes the "
                            f"connection loop"))
                elif (isinstance(sub, ast.Subscript)
                      and isinstance(sub.ctx, ast.Load)
                      and isinstance(sub.value, ast.Name)
                      and sub.value.id in bufs
                      and not isinstance(sub.slice, ast.Slice)):
                    if not covered(sub.lineno):
                        out.append(mod.violation(
                            "R3", sub,
                            f"single-index read of received buffer "
                            f"{sub.value.id!r} with no dominating len() "
                            f"guard — IndexError on a truncated frame"))
    return out


# --- R4: lock discipline -----------------------------------------------------

_BLOCKING_SOCKET_ATTRS = {"recv", "recvfrom", "sendall", "sendto",
                          "accept", "connect", "makefile"}
_LOCK_CTORS = {"Lock", "RLock"}


def _locky(name: Optional[str]) -> bool:
    if not name:
        return False
    tail = name.split(".")[-1].lower()
    return "lock" in tail or "mutex" in tail or tail in ("lk", "_lk", "mu")


def _known_locks(mod: ParsedModule) -> set[str]:
    """Attribute/name tails assigned a threading.Lock()/RLock()."""
    out: set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        d = dotted(node.value.func) or ""
        if d.split(".")[-1] not in _LOCK_CTORS:
            continue
        for t in node.targets:
            tail = (dotted(t) or "").split(".")[-1]
            if tail:
                out.add(tail)
    return out


def check_r4(modules: list[ParsedModule], root: str) -> list[Violation]:
    out: list[Violation] = []
    for mod in modules:
        known = _known_locks(mod)

        def lockish(expr: ast.AST) -> bool:
            d = dotted(expr)
            return bool(d) and (_locky(d) or d.split(".")[-1] in known)

        for scope, node in walk_scoped(mod.tree):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute):
                if node.func.attr in ("acquire", "release") and lockish(
                        node.func.value):
                    out.append(mod.violation(
                        "R4", node,
                        f"bare .{node.func.attr}() on "
                        f"{dotted(node.func.value)!r} — use `with` so the "
                        f"release survives exceptions (and lockgraph can "
                        f"see the critical section)"))
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lock_items = [i for i in node.items
                          if lockish(i.context_expr)]
            if not lock_items:
                continue
            held = {dotted(i.context_expr) for i in lock_items}
            for sub in body_nodes(node, into_nested=False):
                if not isinstance(sub, ast.Call):
                    continue
                d = dotted(sub.func)
                if not d:
                    continue
                parts = d.split(".")
                attr = parts[-1]
                recv = ".".join(parts[:-1])
                msg = None
                if d == "time.sleep":
                    msg = "time.sleep under a held lock"
                elif attr in _BLOCKING_SOCKET_ATTRS and len(parts) > 1:
                    msg = f"blocking socket call .{attr}() under a held lock"
                elif attr in ("get", "put") and "queue" in recv.lower():
                    blockless = any(
                        kw.arg == "block"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is False
                        for kw in sub.keywords) or (
                        sub.args and isinstance(sub.args[0], ast.Constant)
                        and sub.args[0].value is False)
                    if not blockless:
                        msg = (f"blocking queue .{attr}() under a held "
                               f"lock")
                elif attr in ("wait", "wait_connected") and \
                        recv not in held and _locky(recv) is False:
                    if attr == "wait_connected" or (
                            recv and ("event" in recv.lower()
                                      or "cond" in recv.lower()
                                      or "future" in recv.lower())):
                        msg = f".{attr}() under a held lock"
                elif attr == "join" and recv and (
                        "thread" in recv.lower() or "worker" in recv.lower()
                        or "proc" in recv.lower()):
                    msg = "thread join under a held lock"
                if msg:
                    out.append(mod.violation(
                        "R4", sub,
                        f"{msg} ({sorted(held)}) — every other thread "
                        f"touching this lock stalls for the full wait"))
    return out


# --- R5: telemetry hygiene ---------------------------------------------------


def check_r5(modules: list[ParsedModule], root: str) -> list[Violation]:
    out: list[Violation] = []
    for mod in modules:
        counters: set[str] = set()
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Call):
                d = dotted(stmt.value.func) or ""
                if d.endswith("REGISTRY.counter"):
                    counters.update(
                        t.id for t in stmt.targets
                        if isinstance(t, ast.Name))
        for scope, node in walk_scoped(mod.tree):
            if isinstance(node, ast.Call):
                d = dotted(node.func) or ""
                parts = d.split(".")
                # registration must happen at module scope
                if (len(parts) >= 2 and parts[-2] == "REGISTRY"
                        and parts[-1] in ("counter", "gauge", "histogram")
                        and scope):
                    out.append(mod.violation(
                        "R5", node,
                        f"metric family {parts[-1]} registered inside "
                        f"{scope!r} — register once at module scope so "
                        f"re-construction can't fork the family"))
                # counters never go down
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "dec"):
                    chain = d or ""
                    head = chain.split(".")[0]
                    if head in counters or ".labels." in f".{chain}.":
                        if head in counters:
                            out.append(mod.violation(
                                "R5", node,
                                f"counter {head!r} .dec()'d — counters "
                                f"are monotonic; use a gauge"))
        # span scopes must be context-managed or explicitly recorded
        for scope, fn in walk_scoped(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            has_record = any(
                isinstance(n, ast.Call)
                and (dotted(n.func) or "").endswith("record_span")
                for n in body_nodes(fn))
            with_subjects: set[str] = set()
            for n in body_nodes(fn):
                if isinstance(n, (ast.With, ast.AsyncWith)):
                    for item in n.items:
                        d = dotted(item.context_expr)
                        if d:
                            with_subjects.add(d)
            enters = exits = 0
            for n in body_nodes(fn):
                if isinstance(n, ast.Call) and isinstance(
                        n.func, ast.Attribute):
                    if n.func.attr == "__enter__":
                        enters += 1
                    elif n.func.attr == "__exit__":
                        exits += 1
                if not isinstance(n, ast.Assign):
                    continue
                if not isinstance(n.value, ast.Call):
                    continue
                d = dotted(n.value.func) or ""
                if d.split(".")[-1] not in ("root_scope", "child_scope",
                                            "SpanScope"):
                    continue
                tgt = n.targets[0]
                tname = dotted(tgt)
                returned = tname and any(
                    isinstance(r, ast.Return) and r.value is not None
                    and tname in names_in(r.value)
                    for r in body_nodes(fn))
                if tname and (tname in with_subjects or has_record
                              or returned):
                    continue
                # scope value used directly in `with` on a later line?
                out.append(mod.violation(
                    "R5", n,
                    f"trace scope assigned to {tname!r} but never "
                    f"entered via `with` nor explicitly record_span'd — "
                    f"a half-opened span never reaches the ring"))
            if enters != exits:
                out.append(mod.violation(
                    "R5", fn,
                    f"unbalanced manual span __enter__/__exit__ "
                    f"({enters} vs {exits}) in one function"))
    return out


# --- R6: config-key drift ----------------------------------------------------

_SECTION_RE = re.compile(r"^\[([A-Za-z_][A-Za-z0-9_]*)\]")
_INI_KEY_RE = re.compile(r"^;?\s*([a-z_][a-z0-9_]*)\s*=")
_GETTERS = {"get", "getint", "getfloat", "getboolean"}


def _family(section: str) -> str:
    base = re.sub(r"\d+$", "", section)
    if base.endswith("_common"):
        base = base[: -len("_common")]
    return base


def _norm_key(key: str) -> str:
    return re.sub(r"^start_nodes_.+$", "start_nodes_N", key)


def _sample_keys(root: str) -> tuple[dict[str, set[str]],
                                     dict[tuple[str, str], int]]:
    fams: dict[str, set[str]] = {}
    lines: dict[tuple[str, str], int] = {}
    section = ""
    path = os.path.join(root, "goworld.ini.sample")
    with open(path, encoding="utf-8") as f:
        for ln, raw in enumerate(f, 1):
            line = raw.rstrip("\n")
            m = _SECTION_RE.match(line.strip())
            if m:
                section = m.group(1)
                continue
            if line.startswith(";"):
                # a commented-out KEY is documented at column 0
                # ("; delivery = pipelined"); indented ';' lines are
                # wrapped prose of an inline comment, never keys
                inner = line[1:].lstrip()
                if inner.startswith(";") or inner.startswith("-"):
                    continue  # double-comment / separator line
                line = inner
            elif line.lstrip().startswith((";", "#")):
                continue
            else:
                line = line.lstrip()
            m2 = _INI_KEY_RE.match(line)
            if m2 and section:
                key = _norm_key(m2.group(1))
                fam = _family(section)
                fams.setdefault(fam, set()).add(key)
                lines.setdefault((fam, key), ln)
    return fams, lines


def _code_keys(mod: ParsedModule) -> dict[str, dict[str, int]]:
    """family -> {key: first line} read in read_config.py, attributed to
    the most recent section-selecting event (linear file structure)."""
    events: list[tuple[int, str]] = []  # (line, family)
    reads: list[tuple[int, str, Optional[str]]] = []  # (line, key, inline fam)
    has_start_nodes_reader = "start_nodes_" in mod.source

    def const_str(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.JoinedStr):
            # f"dispatcher{i}" -> leading constant prefix names the family
            if node.values and isinstance(node.values[0], ast.Constant):
                return str(node.values[0].value)
        return None

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            attr = node.func.attr if isinstance(node.func, ast.Attribute) \
                else (node.func.id if isinstance(node.func, ast.Name)
                      else "")
            if attr == "has_section" and node.args:
                s = const_str(node.args[0])
                if s:
                    events.append((node.lineno, _family(s)))
            elif attr == "merged" and node.args:
                s = const_str(node.args[0])
                if s:
                    events.append((node.lineno, _family(s)))
            elif attr in _GETTERS and node.args:
                key = const_str(node.args[0])
                if key is None:
                    continue
                inline_fam = None
                recv = node.func.value if isinstance(
                    node.func, ast.Attribute) else None
                if isinstance(recv, ast.Subscript):
                    s = const_str(recv.slice)
                    if s:
                        inline_fam = _family(s)
                reads.append((node.lineno, _norm_key(key), inline_fam))
        elif isinstance(node, ast.Subscript):
            # cp["storage"] as a section-selecting event
            base = dotted(node.value)
            if base == "cp":
                s = const_str(node.slice)
                if s:
                    events.append((node.lineno, _family(s)))

    events.sort()
    out: dict[str, dict[str, int]] = {}
    for line, key, inline_fam in sorted(reads):
        fam = inline_fam
        if fam is None:
            prior = [f for l, f in events if l <= line]
            fam = prior[-1] if prior else ""
        if fam:
            out.setdefault(fam, {}).setdefault(key, line)
    if has_start_nodes_reader:
        for fam in ("storage", "kvdb"):
            out.setdefault(fam, {}).setdefault("start_nodes_N", 1)
    return out


def check_r6(modules: list[ParsedModule], root: str) -> list[Violation]:
    mod = next((m for m in modules
                if m.path == "goworld_tpu/config/read_config.py"), None)
    if mod is None:
        return []
    sample_path = os.path.join(root, "goworld.ini.sample")
    if not os.path.exists(sample_path):
        return []
    sample, sample_lines = _sample_keys(root)
    code = _code_keys(mod)
    out: list[Violation] = []
    for fam, keys in sorted(code.items()):
        for key, line in sorted(keys.items()):
            if key not in sample.get(fam, set()):
                out.append(mod.violation(
                    "R6", line,
                    f"config key [{fam}] {key} is read here but not "
                    f"documented in goworld.ini.sample — operators can't "
                    f"discover it"))
    for fam, keys in sorted(sample.items()):
        for key in sorted(keys):
            if key not in code.get(fam, {}):
                ln = sample_lines.get((fam, key), 1)
                out.append(Violation(
                    "R6", "goworld.ini.sample", ln, f"[{fam}]",
                    f"key {key} documented in goworld.ini.sample is never "
                    f"read by config/read_config.py — drift or typo"))
    return out


CHECKERS = {
    "R1": check_r1,
    "R2": check_r2,
    "R3": check_r3,
    "R4": check_r4,
    "R5": check_r5,
    "R6": check_r6,
}

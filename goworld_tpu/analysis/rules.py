"""The six engine-specific gwlint rules.

Each checker takes the parsed package (list[ParsedModule]) plus the repo
root and returns Violations.  All checks are heuristic AST passes tuned to
THIS codebase's idioms; anything they over-report is suppressed in the
committed baseline with a written justification, so precision bugs cost a
review line, never a silent pass.  The rules:

- **R1 jit-hygiene** — whole-program: functions reachable from
  ``jax.jit`` / ``vmap`` / ``shard_map`` / ``lax.scan``-style callsites
  (cross-module call graph, ``self.*`` methods resolved) must not call
  host-sync primitives (``.item()``, ``float()`` on non-constants,
  ``np.asarray/np.array``, ``jax.device_get``, ``block_until_ready``) or
  mutate module-level state under trace.
- **R2 hot-path shape** — functions on the tick/collect/route/demux hot
  paths (``@hot_path``-decorated or listed in ``HOT_PATHS``) must not
  contain per-item Python loops over non-constant iterables or
  per-record ``struct.pack`` inside a loop.
- **R3 parse-bounds** — in ``netutil/`` and ``proto/``, unpack/index
  reads of received buffers must be dominated by a ``len()`` guard or an
  enclosing try/except that catches the short-read error.
- **R4 lock discipline** — lock acquisition goes through ``with``; no
  ``time.sleep`` / socket send/recv / blocking queue op lexically inside
  a held-lock region.
- **R5 telemetry hygiene** — metric families register at module scope,
  counters never ``.dec()``, trace spans are context-managed (or
  explicitly recorded) rather than half-entered.
- **R6 config-key drift** — every ini key read in
  ``config/read_config.py`` exists in ``goworld.ini.sample`` and vice
  versa (numbered sections fold into their family; ``start_nodes_N``
  matches the prefix reader).
- **R7 proto conformance** — whole-program wire-schema agreement: every
  pack site (ordered ``append_*`` calls on a locally built Packet sent
  with a ``MsgType.X`` literal) and every handler-side unpack site
  (ordered ``read_*`` calls in ``dispatcher/``, ``gate/``, ``game/``,
  ``rebalance/``, attributed per msgtype via handler tables and
  ``msgtype == MsgType.X`` branches) must match the declared field
  sequence in ``proto/schema.py``; the schema digest must match the
  ``SCHEMA_HISTORY`` pin for the current ``PROTO_VERSION`` — a layout
  edit without a version bump fails here, not in production.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator, Optional

from goworld_tpu.analysis.core import ParsedModule, Violation

# --- shared AST helpers ------------------------------------------------------


def dotted(node: ast.AST) -> Optional[str]:
    """"a.b.c" for a Name/Attribute chain; None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def module_name(path: str) -> str:
    mod = path[:-3].replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def import_map(mod: ParsedModule) -> dict[str, str]:
    """Local alias -> fully qualified target (relative imports resolved)."""
    modname = module_name(mod.path)
    package = modname if mod.path.endswith("__init__.py") else (
        modname.rsplit(".", 1)[0] if "." in modname else "")
    out: dict[str, str] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    out[a.asname] = a.name
                else:
                    out[a.name.split(".")[0]] = a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                parts = package.split(".") if package else []
                if node.level > 1:
                    parts = parts[: len(parts) - (node.level - 1)]
                base = ".".join(parts + ([node.module] if node.module else []))
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{base}.{a.name}" if base else a.name
    return out


def walk_scoped(tree: ast.AST) -> Iterator[tuple[str, ast.AST]]:
    """Yield (enclosing dotted scope, node) for every node."""

    def visit(node: ast.AST, scope: str) -> Iterator[tuple[str, ast.AST]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                sub = f"{scope}.{child.name}" if scope else child.name
                yield scope, child
                yield from visit(child, sub)
            else:
                yield scope, child
                yield from visit(child, scope)

    yield from visit(tree, "")


def body_nodes(fn: ast.AST, into_nested: bool = True) -> Iterator[ast.AST]:
    """Every node lexically inside ``fn``'s body (optionally skipping
    nested function/lambda bodies — deferred execution)."""

    def visit(node: ast.AST) -> Iterator[ast.AST]:
        for child in ast.iter_child_nodes(node):
            yield child
            if not into_nested and isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
                continue
            yield from visit(child)

    for stmt in getattr(fn, "body", []):
        yield stmt
        yield from visit(stmt)


# --- R1: jit hygiene ---------------------------------------------------------

# wrapper name -> positions of the traced-function argument(s)
_JIT_WRAPPERS = {
    "jit": (0,), "pjit": (0,), "pmap": (0,), "vmap": (0,),
    "shard_map": (0,), "vmapped_position_tick": (0,),
    "grad": (0,), "value_and_grad": (0,), "remat": (0,), "checkpoint": (0,),
    "scan": (0,), "while_loop": (0, 1), "fori_loop": (2,), "cond": (1, 2),
}
# functions whose function-args run on HOST, not under trace
_HOST_CALLBACK_FUNCS = {"pure_callback", "io_callback", "host_callback",
                        "debug_callback"}
_NUMPY_HOST_FUNCS = {"asarray", "array"}


class _ProgramIndex:
    def __init__(self, modules: list[ParsedModule]) -> None:
        self.modules = {module_name(m.path): m for m in modules}
        # modname -> {qualname: def node}
        self.defs: dict[str, dict[str, ast.AST]] = {}
        self.classes: dict[str, set[str]] = {}
        self.imports: dict[str, dict[str, str]] = {}
        self.np_aliases: dict[str, set[str]] = {}
        for name, m in self.modules.items():
            defs: dict[str, ast.AST] = {}
            classes: set[str] = set()
            for scope, node in walk_scoped(m.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{scope}.{node.name}" if scope else node.name
                    defs[qual] = node
                elif isinstance(node, ast.ClassDef):
                    qual = f"{scope}.{node.name}" if scope else node.name
                    classes.add(qual)
            self.defs[name] = defs
            self.classes[name] = classes
            imp = import_map(m)
            self.imports[name] = imp
            self.np_aliases[name] = {
                a for a, tgt in imp.items()
                if tgt == "numpy" or tgt.startswith("numpy.")}

    def resolve(self, modname: str, scope: str,
                ref: str) -> Optional[tuple[str, str]]:
        """Resolve a dotted reference at ``scope`` in ``modname`` to a
        package function: (modname, qualname), or None."""
        defs = self.defs.get(modname, {})
        parts = ref.split(".")
        if parts[0] in ("self", "cls") and len(parts) == 2:
            # method of the nearest enclosing class in the scope chain
            sp = scope.split(".")
            for i in range(len(sp), 0, -1):
                cand = ".".join(sp[:i])
                if cand in self.classes.get(modname, ()):
                    qual = f"{cand}.{parts[1]}"
                    if qual in defs:
                        return (modname, qual)
            return None
        if len(parts) == 1:
            # lexical scope chain, innermost first
            sp = scope.split(".") if scope else []
            for i in range(len(sp), -1, -1):
                cand = ".".join(sp[:i] + [ref]) if i else ref
                if cand in defs:
                    return (modname, cand)
            tgt = self.imports.get(modname, {}).get(ref)
            if tgt and tgt.startswith("goworld_tpu"):
                if "." in tgt:
                    tmod, tname = tgt.rsplit(".", 1)
                    if tname in self.defs.get(tmod, {}):
                        return (tmod, tname)
            return None
        # alias.func: alias must name a package module
        tgt = self.imports.get(modname, {}).get(parts[0])
        if tgt and tgt.startswith("goworld_tpu") and len(parts) == 2:
            if parts[1] in self.defs.get(tgt, {}):
                return (tgt, parts[1])
        return None


def _unwrap_partial(arg: ast.AST) -> ast.AST:
    if isinstance(arg, ast.Call):
        inner = dotted(arg.func)
        if inner and inner.split(".")[-1] == "partial" and arg.args:
            return arg.args[0]
    return arg


def _resolve_traced_arg(index: _ProgramIndex, modname: str, scope: str,
                        arg: ast.AST) -> Optional[tuple[str, str]]:
    """Resolve the function argument of a jit-wrapper call, chasing one
    level of `body = functools.partial(f, ...)` local binding."""
    arg = _unwrap_partial(arg)
    ref = dotted(arg)
    if not ref:
        return None
    hit = index.resolve(modname, scope, ref)
    if hit:
        return hit
    # local variable: find its binding assignment in the enclosing def
    encl = index.defs.get(modname, {}).get(scope)
    if encl is not None and "." not in ref:
        for node in body_nodes(encl):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == ref
                    for t in node.targets):
                src = _unwrap_partial(node.value)
                ref2 = dotted(src)
                if ref2 and ref2 != ref:
                    hit = index.resolve(modname, scope, ref2)
                    if hit:
                        return hit
    return None


def _jit_roots(index: _ProgramIndex) -> set[tuple[str, str]]:
    roots: set[tuple[str, str]] = set()
    for modname, mod in index.modules.items():
        for scope, node in walk_scoped(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{scope}.{node.name}" if scope else node.name
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    d = dotted(target)
                    if d and d.split(".")[-1] in _JIT_WRAPPERS:
                        roots.add((modname, qual))
                    elif (isinstance(dec, ast.Call)
                          and d and d.split(".")[-1] == "partial"
                          and dec.args):
                        inner = dotted(dec.args[0])
                        if inner and inner.split(".")[-1] in _JIT_WRAPPERS:
                            roots.add((modname, qual))
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if not d:
                continue
            name = d.split(".")[-1]
            if name not in _JIT_WRAPPERS:
                continue
            for pos in _JIT_WRAPPERS[name]:
                if pos >= len(node.args):
                    continue
                hit = _resolve_traced_arg(
                    index, modname, scope, node.args[pos])
                if hit:
                    roots.add(hit)
    return roots


def _reachable(index: _ProgramIndex,
               roots: set[tuple[str, str]]) -> set[tuple[str, str]]:
    seen = set(roots)
    frontier = list(roots)
    while frontier:
        modname, qual = frontier.pop()
        fn = index.defs[modname].get(qual)
        if fn is None:
            continue
        mod = index.modules[modname]
        scope = qual
        # host-callback args are excluded from reference resolution
        excluded: set[int] = set()
        for node in body_nodes(fn):
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d and d.split(".")[-1] in _HOST_CALLBACK_FUNCS:
                    for a in node.args:
                        for sub in ast.walk(a):
                            excluded.add(id(sub))
        for node in body_nodes(fn):
            if id(node) in excluded:
                continue
            ref = None
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                ref = node.id
            elif isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, ast.Load):
                ref = dotted(node)
            if not ref:
                continue
            hit = index.resolve(modname, scope, ref)
            if hit and hit not in seen:
                seen.add(hit)
                frontier.append(hit)
        del mod
    return seen


def _module_mutables(mod: ParsedModule) -> set[str]:
    """Module-level names bound to obviously-mutable containers."""
    out: set[str] = set()
    for stmt in mod.tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = [t for t in stmt.targets if isinstance(t, ast.Name)]
            value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name) and stmt.value is not None:
            targets = [stmt.target]
            value = stmt.value
        else:
            continue
        mutable = isinstance(value, (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp, ast.SetComp))
        if isinstance(value, ast.Call):
            d = dotted(value.func)
            if d and d.split(".")[-1] in ("list", "dict", "set",
                                          "defaultdict", "deque",
                                          "OrderedDict"):
                mutable = True
        if mutable:
            out.update(t.id for t in targets)
    return out


_MUTATOR_ATTRS = {"append", "extend", "update", "setdefault", "add",
                  "pop", "popitem", "insert", "remove", "clear"}


def check_r1(modules: list[ParsedModule], root: str) -> list[Violation]:
    index = _ProgramIndex(modules)
    reach = _reachable(index, _jit_roots(index))
    out: list[Violation] = []
    by_mod: dict[str, list[str]] = {}
    for modname, qual in reach:
        by_mod.setdefault(modname, []).append(qual)
    for modname, quals in by_mod.items():
        mod = index.modules[modname]
        np_alias = index.np_aliases[modname]
        mutables = _module_mutables(mod)
        for qual in quals:
            fn = index.defs[modname][qual]
            for node in body_nodes(fn):
                if isinstance(node, ast.Global):
                    out.append(mod.violation(
                        "R1", node,
                        "jit-reachable function rebinds module state "
                        "via `global` — side effects under trace run "
                        "once, at trace time"))
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(
                        node, ast.Assign) else [node.target]
                    for t in targets:
                        if isinstance(t, ast.Subscript):
                            base = dotted(t.value)
                            if base in mutables:
                                out.append(mod.violation(
                                    "R1", node,
                                    f"mutates module-level container "
                                    f"{base!r} under trace — runs at "
                                    f"trace time, not per step"))
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item" and not node.args):
                    out.append(mod.violation(
                        "R1", node,
                        ".item() host-syncs the device stream inside a "
                        "jit-reachable function"))
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr == "block_until_ready"):
                    out.append(mod.violation(
                        "R1", node,
                        "block_until_ready() host-syncs inside a "
                        "jit-reachable function"))
                elif d and d.split(".")[-1] == "device_get":
                    out.append(mod.violation(
                        "R1", node,
                        "jax.device_get host-syncs inside a jit-reachable "
                        "function"))
                elif (d and "." in d and d.split(".")[0] in np_alias
                      and d.split(".")[-1] in _NUMPY_HOST_FUNCS):
                    out.append(mod.violation(
                        "R1", node,
                        f"{d}() materializes on host inside a "
                        f"jit-reachable function (traced values would "
                        f"host-sync; use jnp, or hoist to the host side)"))
                elif (d == "float" and len(node.args) == 1
                      and not isinstance(node.args[0], ast.Constant)):
                    out.append(mod.violation(
                        "R1", node,
                        "float(x) on a non-constant host-syncs if x is "
                        "traced"))
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in _MUTATOR_ATTRS):
                    base = dotted(node.func.value)
                    if base in mutables:
                        out.append(mod.violation(
                            "R1", node,
                            f"mutates module-level container {base!r} "
                            f"under trace — runs at trace time, not per "
                            f"step"))
    return out


# --- R2: hot-path shape ------------------------------------------------------

# path -> function names (bare, matched against the tail of the dotted
# symbol).  These are the per-tick collect/route/demux/fan-out paths the
# fanout and pinned floors measure.
HOT_PATHS: dict[str, set[str]] = {
    "goworld_tpu/entity/slabs.py": {
        "collect_sync_selection", "pack_sync", "collect_sync",
        "run_tick_batches", "set_position_yaw",
        # Adaptive per-client sync (ISSUE 14): the tiered collect runs
        # every position-sync collection — selection, quantization,
        # baseline advance and wire pack must stay vectorized.
        "_collect_sync_tiered", "_emit_mask", "_pack_rows",
        "retier_host",
    },
    "goworld_tpu/dispatcher/service.py": {
        "_handle_sync_position_yaw_from_client", "_send_pending_syncs",
        "_flush_pending_sync", "_route_to_gate",
    },
    "goworld_tpu/gate/service.py": {
        "_handle_sync_on_clients", "_handle_sync_delta_on_clients",
        "_flush_pending_syncs",
    },
    "goworld_tpu/ops/neighbor.py": {
        "neighbor_step", "build_tables", "diff_events",
        # Fused entity-logic launch ([aoi] fuse_logic): these bodies must
        # stay loop-free — the trace-time program unroll lives in
        # _apply_fused_logic, outside the guarded set by design.
        "_step_packed_fused_jnp", "_step_packed_fused_pallas",
        # The [sync] tier pass rides the step launch: loop-free jnp.
        "_tier_pass",
        # Device-resident tick (ISSUE 19): the Pallas event kernel body
        # (trace-time Python must stay loop-free — in-kernel iteration is
        # lax.fori_loop) and the edge-verdict pass that rides the step.
        "_event_kernel", "_edge_verdicts",
    },
    "goworld_tpu/parallel/spatial.py": {
        "_spatial_step_fused_impl",
        # Pallas strip tier (ISSUE 15): the strip-local step/drain bodies
        # and the replicated seam-free guard run every spatial tick —
        # loop-free jnp by design (the ring-permutation comprehension
        # lives in _exchange_halo, O(devices) like the pre-existing
        # _spatial_step_impl, outside the guarded set).
        "_spatial_step_pallas_impl", "_spatial_step_pallas_fused_impl",
        "_spatial_drain_bits", "_build_table_strip", "_fast_guard_strip",
        # In-kernel drain (ISSUE 19): the slot/own plane scatter feeding
        # the kernel's pair emission runs every strip tick.
        "_scatter_slotown",
    },
    # Fused interest-edge delivery (ISSUE 19): the decode split and the
    # device edge-snapshot build run every delivery tick — vectorized
    # numpy only; the thin guarded per-row loop lives in _apply_edge_rows,
    # outside the guarded set by design (it is the contractual per-entity
    # interest bookkeeping, already minimal).
    "goworld_tpu/entity/aoi/batched.py": {
        "_deliver_fused", "_build_device_edges",
    },
    "goworld_tpu/parallel/mesh.py": {
        "_sharded_step_fused",
    },
    # Scenario matrix (ISSUE 16): each scenario's per-tick world update
    # runs every scenario tick and must stay vectorized numpy — the
    # bounded per-op service loop lives in service_heavy._issue_ops,
    # outside the guarded set by design (64 ops/tick by config, not
    # O(entities)).
    "goworld_tpu/scenarios/battle_royale.py": {"tick"},
    "goworld_tpu/scenarios/hotspot.py": {"tick"},
    "goworld_tpu/scenarios/service_heavy.py": {"tick"},
    # Whole-space handoff (ISSUE 18): the snapshot/restore bodies run with
    # every member's dispatcher stream PARKED — wall-clock here is client
    # stall, so per-member work must stay slab/struct ops (the per-member
    # loops that remain are baselined with their boundedness reasons).
    "goworld_tpu/entity/entity_manager.py": {
        "pack_space", "restore_space_bundle",
        # Columnar batch persistence (ISSUE 19): the per-column gather
        # core — loop-free; the per-entity cache stitch stays in
        # primed_column_snapshot outside the guarded set (dict stores).
        "_gather_column",
    },
    "goworld_tpu/rebalance/migrator.py": {
        "handle_space_command", "_pack_and_send", "on_space_data",
        "_tick_spaces",
    },
    # Black-box history ring (ISSUE 20): the frame encode runs on every
    # history cadence in every process — header pack + slice assign into
    # a grow-only buffer, no loops, no per-frame object churn (the
    # payload walk lives in _collect, off the guarded set: it is the
    # snapshot-cadence collector, not the encode).
    "goworld_tpu/telemetry/history.py": {"_encode_frame"},
}


def _is_const_bounded(it: ast.AST) -> bool:
    if isinstance(it, (ast.Tuple, ast.List, ast.Set, ast.Dict, ast.Constant)):
        return True
    if isinstance(it, ast.Call):
        d = dotted(it.func)
        if d in ("range", "enumerate", "reversed", "zip") and all(
                _is_const_bounded(a) or isinstance(a, ast.Constant)
                for a in it.args):
            return True
    return False


def _hot_functions(mod: ParsedModule) -> list[tuple[str, ast.AST]]:
    listed = HOT_PATHS.get(mod.path, set())
    out = []
    for scope, node in walk_scoped(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        qual = f"{scope}.{node.name}" if scope else node.name
        decorated = any(
            (dotted(dec) or "").split(".")[-1] == "hot_path"
            for dec in node.decorator_list)
        if decorated or node.name in listed:
            out.append((qual, node))
    return out


def check_r2(modules: list[ParsedModule], root: str) -> list[Violation]:
    out: list[Violation] = []
    for mod in modules:
        for qual, fn in _hot_functions(mod):
            loop_spans: list[tuple[int, int]] = []
            for node in body_nodes(fn):
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    loop_spans.append(
                        (node.lineno, node.end_lineno or node.lineno))
                    if not _is_const_bounded(node.iter):
                        src = ast.unparse(node.iter)
                        out.append(mod.violation(
                            "R2", node,
                            f"per-item Python loop over {src!r} on a "
                            f"hot path — vectorize or prove the iterable "
                            f"O(gates), not O(entities)"))
                elif isinstance(node, ast.While):
                    loop_spans.append(
                        (node.lineno, node.end_lineno or node.lineno))
                    out.append(mod.violation(
                        "R2", node,
                        "while-loop on a hot path — prove bounded or "
                        "vectorize"))
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.DictComp, ast.GeneratorExp)):
                    for gen in node.generators:
                        if not _is_const_bounded(gen.iter):
                            src = ast.unparse(gen.iter)
                            out.append(mod.violation(
                                "R2", node,
                                f"per-item comprehension over {src!r} on "
                                f"a hot path"))
            for node in body_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if not d or d.split(".")[-1] not in ("pack", "pack_into"):
                    continue
                parts = d.split(".")
                packish = (parts[0] == "struct"
                           or "struct" in parts[-2].lower()
                           if len(parts) > 1 else False)
                if not packish:
                    continue
                in_loop = any(lo < node.lineno <= hi for lo, hi in loop_spans)
                if in_loop:
                    out.append(mod.violation(
                        "R2", node,
                        f"per-record {d} inside a loop on a hot path — "
                        f"build columns and pack once"))
    return out


# --- R3: parse bounds --------------------------------------------------------

_BUF_PARAM_NAMES = {
    "data", "buf", "buff", "buffer", "payload", "raw", "b", "msg", "frame",
    "chunk", "body", "blob", "segment", "seg", "datagram", "wire", "packed",
}
_RECV_FUNCS = {"recv", "recvfrom", "recv_exact", "read", "read_exact",
               "readexactly"}
_SHORT_READ_ERRORS = {"error", "struct", "IndexError", "ValueError",
                      "Exception", "BaseException", "KeyError"}


def _buffer_names(fn: ast.AST) -> set[str]:
    bufs = {a.arg for a in _all_args(fn) if a.arg in _BUF_PARAM_NAMES}
    # propagate through simple assignments (memoryview(data), data[4:], recv)
    changed = True
    while changed:
        changed = False
        for node in body_nodes(fn):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name) or tgt.id in bufs:
                continue
            src_names = names_in(node.value)
            from_recv = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in _RECV_FUNCS
                for n in ast.walk(node.value))
            if (src_names & bufs) or from_recv:
                bufs.add(tgt.id)
                changed = True
    return bufs


def _all_args(fn: ast.AST) -> list[ast.arg]:
    a = fn.args
    return [*a.posonlyargs, *a.args, *a.kwonlyargs]


_GUARD_FN_RE = re.compile(r"(need|check|require|ensure|guard|bounds)",
                          re.IGNORECASE)


def _guard_lines(fn: ast.AST, bufs: set[str]) -> list[int]:
    """Lines where a len() of a buffer name occurs, or where the buffer
    is passed to a bounds-guard helper (``_need(data, off, 8)`` — the
    conventional names are matched by _GUARD_FN_RE)."""
    out = []
    for node in body_nodes(fn):
        if not isinstance(node, ast.Call):
            continue
        if (isinstance(node.func, ast.Name) and node.func.id == "len"
                and node.args and (names_in(node.args[0]) & bufs)):
            out.append(node.lineno)
            continue
        d = dotted(node.func)
        if (d and _GUARD_FN_RE.search(d.split(".")[-1])
                and any(names_in(a) & bufs for a in node.args)):
            out.append(node.lineno)
    return out


def _try_spans(fn: ast.AST) -> list[tuple[int, int]]:
    spans = []
    for node in body_nodes(fn):
        if not isinstance(node, ast.Try):
            continue
        catches = False
        for h in node.handlers:
            if h.type is None:
                catches = True
            else:
                for t in ([h.type.elts] if isinstance(h.type, ast.Tuple)
                          else [[h.type]]):
                    for e in t:
                        d = dotted(e) or ""
                        if d.split(".")[0] in _SHORT_READ_ERRORS or \
                                d.split(".")[-1] in _SHORT_READ_ERRORS:
                            catches = True
        if catches and node.body:
            lo = node.body[0].lineno
            hi = max(s.end_lineno or s.lineno for s in node.body)
            spans.append((lo, hi))
    return spans


def check_r3(modules: list[ParsedModule], root: str) -> list[Violation]:
    out: list[Violation] = []
    for mod in modules:
        if not (mod.path.startswith("goworld_tpu/netutil/")
                or mod.path.startswith("goworld_tpu/proto/")):
            continue
        for scope, node in walk_scoped(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            bufs = _buffer_names(node)
            if not bufs:
                continue
            guards = _guard_lines(node, bufs)
            tries = _try_spans(node)

            def covered(line: int) -> bool:
                # <= : `if len(parts) == 3 and parts[0] ...` guards
                # same-line reads via short-circuit evaluation
                return (any(g <= line for g in guards)
                        or any(lo <= line <= hi for lo, hi in tries))

            for sub in body_nodes(node):
                if isinstance(sub, ast.Call):
                    d = dotted(sub.func)
                    risky = None
                    if d and d.split(".")[-1] in ("unpack", "unpack_from"):
                        if any(names_in(a) & bufs for a in sub.args):
                            risky = f"{d}()"
                    elif d == "int.from_bytes" and sub.args and (
                            names_in(sub.args[0]) & bufs):
                        risky = "int.from_bytes()"
                    if risky and not covered(sub.lineno):
                        out.append(mod.violation(
                            "R3", sub,
                            f"{risky} reads a received buffer "
                            f"({sorted(names_in(sub) & bufs)}) with no "
                            f"dominating len() guard or short-read "
                            f"try/except — a truncated frame crashes the "
                            f"connection loop"))
                elif (isinstance(sub, ast.Subscript)
                      and isinstance(sub.ctx, ast.Load)
                      and isinstance(sub.value, ast.Name)
                      and sub.value.id in bufs
                      and not isinstance(sub.slice, ast.Slice)):
                    if not covered(sub.lineno):
                        out.append(mod.violation(
                            "R3", sub,
                            f"single-index read of received buffer "
                            f"{sub.value.id!r} with no dominating len() "
                            f"guard — IndexError on a truncated frame"))
    return out


# --- R4: lock discipline -----------------------------------------------------

_BLOCKING_SOCKET_ATTRS = {"recv", "recvfrom", "sendall", "sendto",
                          "accept", "connect", "makefile"}
_LOCK_CTORS = {"Lock", "RLock"}


def _locky(name: Optional[str]) -> bool:
    if not name:
        return False
    tail = name.split(".")[-1].lower()
    return "lock" in tail or "mutex" in tail or tail in ("lk", "_lk", "mu")


def _known_locks(mod: ParsedModule) -> set[str]:
    """Attribute/name tails assigned a threading.Lock()/RLock()."""
    out: set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        d = dotted(node.value.func) or ""
        if d.split(".")[-1] not in _LOCK_CTORS:
            continue
        for t in node.targets:
            tail = (dotted(t) or "").split(".")[-1]
            if tail:
                out.add(tail)
    return out


def check_r4(modules: list[ParsedModule], root: str) -> list[Violation]:
    out: list[Violation] = []
    for mod in modules:
        known = _known_locks(mod)

        def lockish(expr: ast.AST) -> bool:
            d = dotted(expr)
            return bool(d) and (_locky(d) or d.split(".")[-1] in known)

        for scope, node in walk_scoped(mod.tree):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute):
                if node.func.attr in ("acquire", "release") and lockish(
                        node.func.value):
                    out.append(mod.violation(
                        "R4", node,
                        f"bare .{node.func.attr}() on "
                        f"{dotted(node.func.value)!r} — use `with` so the "
                        f"release survives exceptions (and lockgraph can "
                        f"see the critical section)"))
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lock_items = [i for i in node.items
                          if lockish(i.context_expr)]
            if not lock_items:
                continue
            held = {dotted(i.context_expr) for i in lock_items}
            for sub in body_nodes(node, into_nested=False):
                if not isinstance(sub, ast.Call):
                    continue
                d = dotted(sub.func)
                if not d:
                    continue
                parts = d.split(".")
                attr = parts[-1]
                recv = ".".join(parts[:-1])
                msg = None
                if d == "time.sleep":
                    msg = "time.sleep under a held lock"
                elif attr in _BLOCKING_SOCKET_ATTRS and len(parts) > 1:
                    msg = f"blocking socket call .{attr}() under a held lock"
                elif attr in ("get", "put") and "queue" in recv.lower():
                    blockless = any(
                        kw.arg == "block"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is False
                        for kw in sub.keywords) or (
                        sub.args and isinstance(sub.args[0], ast.Constant)
                        and sub.args[0].value is False)
                    if not blockless:
                        msg = (f"blocking queue .{attr}() under a held "
                               f"lock")
                elif attr in ("wait", "wait_connected") and \
                        recv not in held and _locky(recv) is False:
                    if attr == "wait_connected" or (
                            recv and ("event" in recv.lower()
                                      or "cond" in recv.lower()
                                      or "future" in recv.lower())):
                        msg = f".{attr}() under a held lock"
                elif attr == "join" and recv and (
                        "thread" in recv.lower() or "worker" in recv.lower()
                        or "proc" in recv.lower()):
                    msg = "thread join under a held lock"
                if msg:
                    out.append(mod.violation(
                        "R4", sub,
                        f"{msg} ({sorted(held)}) — every other thread "
                        f"touching this lock stalls for the full wait"))
    return out


# --- R5: telemetry hygiene ---------------------------------------------------


def check_r5(modules: list[ParsedModule], root: str) -> list[Violation]:
    out: list[Violation] = []
    for mod in modules:
        counters: set[str] = set()
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Call):
                d = dotted(stmt.value.func) or ""
                if d.endswith("REGISTRY.counter"):
                    counters.update(
                        t.id for t in stmt.targets
                        if isinstance(t, ast.Name))
        for scope, node in walk_scoped(mod.tree):
            if isinstance(node, ast.Call):
                d = dotted(node.func) or ""
                parts = d.split(".")
                # registration must happen at module scope
                if (len(parts) >= 2 and parts[-2] == "REGISTRY"
                        and parts[-1] in ("counter", "gauge", "histogram")
                        and scope):
                    out.append(mod.violation(
                        "R5", node,
                        f"metric family {parts[-1]} registered inside "
                        f"{scope!r} — register once at module scope so "
                        f"re-construction can't fork the family"))
                # counters never go down
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "dec"):
                    chain = d or ""
                    head = chain.split(".")[0]
                    if head in counters or ".labels." in f".{chain}.":
                        if head in counters:
                            out.append(mod.violation(
                                "R5", node,
                                f"counter {head!r} .dec()'d — counters "
                                f"are monotonic; use a gauge"))
        # span scopes must be context-managed or explicitly recorded
        for scope, fn in walk_scoped(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            has_record = any(
                isinstance(n, ast.Call)
                and (dotted(n.func) or "").endswith("record_span")
                for n in body_nodes(fn))
            with_subjects: set[str] = set()
            for n in body_nodes(fn):
                if isinstance(n, (ast.With, ast.AsyncWith)):
                    for item in n.items:
                        d = dotted(item.context_expr)
                        if d:
                            with_subjects.add(d)
            enters = exits = 0
            for n in body_nodes(fn):
                if isinstance(n, ast.Call) and isinstance(
                        n.func, ast.Attribute):
                    if n.func.attr == "__enter__":
                        enters += 1
                    elif n.func.attr == "__exit__":
                        exits += 1
                if not isinstance(n, ast.Assign):
                    continue
                if not isinstance(n.value, ast.Call):
                    continue
                d = dotted(n.value.func) or ""
                if d.split(".")[-1] not in ("root_scope", "child_scope",
                                            "SpanScope"):
                    continue
                tgt = n.targets[0]
                tname = dotted(tgt)
                returned = tname and any(
                    isinstance(r, ast.Return) and r.value is not None
                    and tname in names_in(r.value)
                    for r in body_nodes(fn))
                if tname and (tname in with_subjects or has_record
                              or returned):
                    continue
                # scope value used directly in `with` on a later line?
                out.append(mod.violation(
                    "R5", n,
                    f"trace scope assigned to {tname!r} but never "
                    f"entered via `with` nor explicitly record_span'd — "
                    f"a half-opened span never reaches the ring"))
            if enters != exits:
                out.append(mod.violation(
                    "R5", fn,
                    f"unbalanced manual span __enter__/__exit__ "
                    f"({enters} vs {exits}) in one function"))
    return out


# --- R6: config-key drift ----------------------------------------------------

_SECTION_RE = re.compile(r"^\[([A-Za-z_][A-Za-z0-9_]*)\]")
_INI_KEY_RE = re.compile(r"^;?\s*([a-z_][a-z0-9_]*)\s*=")
_GETTERS = {"get", "getint", "getfloat", "getboolean"}


def _family(section: str) -> str:
    base = re.sub(r"\d+$", "", section)
    if base.endswith("_common"):
        base = base[: -len("_common")]
    return base


def _norm_key(key: str) -> str:
    return re.sub(r"^start_nodes_.+$", "start_nodes_N", key)


def _sample_keys(root: str) -> tuple[dict[str, set[str]],
                                     dict[tuple[str, str], int]]:
    fams: dict[str, set[str]] = {}
    lines: dict[tuple[str, str], int] = {}
    section = ""
    path = os.path.join(root, "goworld.ini.sample")
    with open(path, encoding="utf-8") as f:
        for ln, raw in enumerate(f, 1):
            line = raw.rstrip("\n")
            m = _SECTION_RE.match(line.strip())
            if m:
                section = m.group(1)
                continue
            if line.startswith(";"):
                # a commented-out KEY is documented at column 0
                # ("; delivery = pipelined"); indented ';' lines are
                # wrapped prose of an inline comment, never keys
                inner = line[1:].lstrip()
                if inner.startswith(";") or inner.startswith("-"):
                    continue  # double-comment / separator line
                line = inner
            elif line.lstrip().startswith((";", "#")):
                continue
            else:
                line = line.lstrip()
            m2 = _INI_KEY_RE.match(line)
            if m2 and section:
                key = _norm_key(m2.group(1))
                fam = _family(section)
                fams.setdefault(fam, set()).add(key)
                lines.setdefault((fam, key), ln)
    return fams, lines


def _code_keys(mod: ParsedModule) -> dict[str, dict[str, int]]:
    """family -> {key: first line} read in read_config.py, attributed to
    the most recent section-selecting event (linear file structure)."""
    events: list[tuple[int, str]] = []  # (line, family)
    reads: list[tuple[int, str, Optional[str]]] = []  # (line, key, inline fam)
    has_start_nodes_reader = "start_nodes_" in mod.source

    def const_str(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.JoinedStr):
            # f"dispatcher{i}" -> leading constant prefix names the family
            if node.values and isinstance(node.values[0], ast.Constant):
                return str(node.values[0].value)
        return None

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            attr = node.func.attr if isinstance(node.func, ast.Attribute) \
                else (node.func.id if isinstance(node.func, ast.Name)
                      else "")
            if attr == "has_section" and node.args:
                s = const_str(node.args[0])
                if s:
                    events.append((node.lineno, _family(s)))
            elif attr == "merged" and node.args:
                s = const_str(node.args[0])
                if s:
                    events.append((node.lineno, _family(s)))
            elif attr in _GETTERS and node.args:
                key = const_str(node.args[0])
                if key is None:
                    continue
                inline_fam = None
                recv = node.func.value if isinstance(
                    node.func, ast.Attribute) else None
                if isinstance(recv, ast.Subscript):
                    s = const_str(recv.slice)
                    if s:
                        inline_fam = _family(s)
                reads.append((node.lineno, _norm_key(key), inline_fam))
        elif isinstance(node, ast.Subscript):
            # cp["storage"] as a section-selecting event
            base = dotted(node.value)
            if base == "cp":
                s = const_str(node.slice)
                if s:
                    events.append((node.lineno, _family(s)))

    events.sort()
    out: dict[str, dict[str, int]] = {}
    for line, key, inline_fam in sorted(reads):
        fam = inline_fam
        if fam is None:
            prior = [f for l, f in events if l <= line]
            fam = prior[-1] if prior else ""
        if fam:
            out.setdefault(fam, {}).setdefault(key, line)
    if has_start_nodes_reader:
        for fam in ("storage", "kvdb"):
            out.setdefault(fam, {}).setdefault("start_nodes_N", 1)
    return out


def check_r6(modules: list[ParsedModule], root: str) -> list[Violation]:
    mod = next((m for m in modules
                if m.path == "goworld_tpu/config/read_config.py"), None)
    if mod is None:
        return []
    sample_path = os.path.join(root, "goworld.ini.sample")
    if not os.path.exists(sample_path):
        return []
    sample, sample_lines = _sample_keys(root)
    code = _code_keys(mod)
    out: list[Violation] = []
    for fam, keys in sorted(code.items()):
        for key, line in sorted(keys.items()):
            if key not in sample.get(fam, set()):
                out.append(mod.violation(
                    "R6", line,
                    f"config key [{fam}] {key} is read here but not "
                    f"documented in goworld.ini.sample — operators can't "
                    f"discover it"))
    for fam, keys in sorted(sample.items()):
        for key in sorted(keys):
            if key not in code.get(fam, {}):
                ln = sample_lines.get((fam, key), 1)
                out.append(Violation(
                    "R6", "goworld.ini.sample", ln, f"[{fam}]",
                    f"key {key} documented in goworld.ini.sample is never "
                    f"read by config/read_config.py — drift or typo"))
    return out


# --- R7: proto conformance ---------------------------------------------------
#
# The schema table (proto/schema.py) is re-read from the AST of the tree
# being linted — never imported — so fixture trees lint exactly like the
# real one.  Only the canonical digest FORMAT comes from the engine
# (schema.digest_of), keeping the runtime digest and the lint digest
# structurally identical by construction.

_SCHEMA_PATH = "goworld_tpu/proto/schema.py"
_MSGTYPES_PATH = "goworld_tpu/proto/msgtypes.py"
#: where handler-side reads are attributed and checked
_R7_UNPACK_PREFIXES = ("goworld_tpu/dispatcher/", "goworld_tpu/gate/",
                       "goworld_tpu/game/", "goworld_tpu/rebalance/")
#: pseudo-msgtype for ``is_gate_redirect(msgtype)`` branches: reads must
#: stay within the [u16 gateid][cid clientid] routing prefix
_REDIRECT_ANY = "<redirect-range>"


class _SchemaEntry:
    __slots__ = ("name", "value", "kinds", "raw", "gate_appended", "line")

    def __init__(self, name: str, value: int, kinds: tuple[str, ...],
                 raw: Optional[str], gate_appended: int, line: int) -> None:
        self.name = name
        self.value = value
        self.kinds = kinds
        self.raw = raw
        self.gate_appended = gate_appended
        self.line = line


class _SchemaTable:
    def __init__(self) -> None:
        self.version: Optional[int] = None
        self.trailer: int = 17
        self.history: dict[int, str] = {}
        self.history_line = 1
        self.types: dict[str, int] = {}  # MsgType member name -> value
        self.entries: dict[str, _SchemaEntry] = {}
        self.redirect_min = 1001
        self.redirect_max = 1499


def _const_int(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    return None


def _field_tuple(node: ast.AST) -> Optional[tuple[str, str]]:
    if (isinstance(node, ast.Tuple) and len(node.elts) == 2
            and all(isinstance(e, ast.Constant)
                    and isinstance(e.value, str) for e in node.elts)):
        return (node.elts[0].value, node.elts[1].value)
    return None


def _msgtype_name(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "MsgType"):
        return node.attr
    return None


def _parse_schema_table(modules: list[ParsedModule]
                        ) -> Optional[tuple[_SchemaTable, ParsedModule]]:
    """Extract the schema table + version constants from the linted tree's
    own proto/schema.py and proto/msgtypes.py ASTs.  Returns None when the
    tree has no schema module (fixture trees exercising other rules)."""
    schema_mod = next((m for m in modules if m.path == _SCHEMA_PATH), None)
    types_mod = next((m for m in modules if m.path == _MSGTYPES_PATH), None)
    if schema_mod is None or types_mod is None:
        return None
    table = _SchemaTable()

    for stmt in types_mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            name, val = stmt.targets[0].id, _const_int(stmt.value)
            if val is None:
                continue
            if name == "PROTO_VERSION":
                table.version = val
            elif name == "REDIRECT_MIN":
                table.redirect_min = val
            elif name == "REDIRECT_MAX":
                table.redirect_max = val
        elif isinstance(stmt, ast.ClassDef) and stmt.name == "MsgType":
            for sub in stmt.body:
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name):
                    val = _const_int(sub.value)
                    if val is not None:
                        table.types[sub.targets[0].id] = val

    prefix: tuple[tuple[str, str], ...] = ()
    for stmt in schema_mod.tree.body:
        targets: list[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(stmt, ast.Assign):
            targets, value = list(stmt.targets), stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if not targets or value is None:
            continue
        tname = targets[0].id if isinstance(targets[0], ast.Name) else ""
        if tname == "TRACE_TRAILER_BYTES":
            v = _const_int(value)
            if v is not None:
                table.trailer = v
        elif tname == "REDIRECT_PREFIX" and isinstance(value, ast.Tuple):
            fields = [_field_tuple(e) for e in value.elts]
            if all(f is not None for f in fields):
                prefix = tuple(f for f in fields if f is not None)
        elif tname == "SCHEMA_HISTORY" and isinstance(value, ast.Dict):
            table.history_line = stmt.lineno
            for k, v2 in zip(value.keys, value.values):
                kv = _const_int(k) if k is not None else None
                if kv is not None and isinstance(v2, ast.Constant) and \
                        isinstance(v2.value, str):
                    table.history[kv] = v2.value
        elif tname == "SCHEMAS" and isinstance(value, ast.Tuple):
            for call in value.elts:
                if not isinstance(call, ast.Call):
                    continue
                fn = dotted(call.func) or ""
                if fn.split(".")[-1] not in ("schema", "_redirect"):
                    continue
                if not call.args:
                    continue
                msg = _msgtype_name(call.args[0])
                if msg is None:
                    continue
                fields = [f for a in call.args[1:]
                          if (f := _field_tuple(a)) is not None]
                if fn.split(".")[-1] == "_redirect":
                    fields = list(prefix) + fields
                raw: Optional[str] = None
                gate_appended = 0
                for kw in call.keywords:
                    if kw.arg == "raw" and isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, str):
                        raw = kw.value.value
                    elif kw.arg == "gate_appended":
                        gate_appended = _const_int(kw.value) or 0
                table.entries[msg] = _SchemaEntry(
                    msg, table.types.get(msg, 0),
                    tuple(k for _n, k in fields), raw, gate_appended,
                    call.lineno)
    return table, schema_mod


# -- statement-order traversal ------------------------------------------------
#
# R7's sequence checks linearize a function body: statements in source
# order, each contributing only its OWN expressions (``_shallow_nodes``),
# with compound statements recursed separately — so a read inside a loop
# or try-block is counted exactly once, in position.


def _stmts_in_order(body: list[ast.stmt]) -> Iterator[ast.stmt]:
    """Yield statements in source order, descending into compound bodies
    (If/For/While/With/Try) but never into nested def/class bodies."""
    for stmt in body:
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, attr, None)
            if isinstance(sub, list) and sub and isinstance(
                    sub[0], ast.stmt):
                yield from _stmts_in_order(sub)
        for h in getattr(stmt, "handlers", []):
            yield from _stmts_in_order(h.body)


def _shallow_nodes(stmt: ast.stmt) -> Iterator[ast.AST]:
    """Every expression node belonging directly to ``stmt`` — child
    statements excluded (they are yielded by _stmts_in_order on their
    own turn, so nothing is visited twice)."""
    todo: list[ast.AST] = []
    for _field, value in ast.iter_fields(stmt):
        if isinstance(value, list):
            todo.extend(v for v in value
                        if isinstance(v, ast.AST)
                        and not isinstance(v, (ast.stmt, ast.excepthandler)))
        elif isinstance(value, ast.AST):
            todo.append(value)
    while todo:
        node = todo.pop()
        yield node
        todo.extend(c for c in ast.iter_child_nodes(node)
                    if not isinstance(c, ast.stmt))


def _append_chains(stmt: ast.stmt) -> list[tuple[str, list[str]]]:
    """(base var, [append kinds in eval order]) for every append chain in
    one statement's own expressions.  ``#raw`` marks append_bytes (a
    raw-region write)."""
    from goworld_tpu.proto.schema import APPEND_TO_KIND

    def is_append(n: ast.AST) -> bool:
        return (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr.startswith("append_"))

    calls = [n for n in _shallow_nodes(stmt) if is_append(n)]
    # a call that appears as another append's receiver is an inner chain
    # link; the remaining calls are chain ROOTS (outermost links)
    inner = {id(c.func.value) for c in calls  # type: ignore[union-attr]
             if is_append(c.func.value)}  # type: ignore[union-attr]
    out: list[tuple[str, list[str]]] = []
    for root in sorted((c for c in calls if id(c) not in inner),
                       key=lambda c: (c.lineno, c.col_offset)):
        chain: list[ast.Call] = []
        cur: ast.AST = root
        while is_append(cur):
            chain.append(cur)  # type: ignore[arg-type]
            cur = cur.func.value  # type: ignore[union-attr]
        base = dotted(cur)
        if base is None:
            continue
        kinds = [APPEND_TO_KIND.get(
            c.func.attr, "#raw" if c.func.attr == "append_bytes"
            else f"?{c.func.attr}")
            for c in reversed(chain)]  # eval order: innermost first
        out.append((base, kinds))
    return out


def _packet_helpers(mod: ParsedModule) -> dict[str, list[str]]:
    """Defs that build one Packet, append fixed kinds, and return it —
    resolvable as pack-prefix seeds (conn.py ``_client_packet``)."""
    out: dict[str, list[str]] = {}
    for _scope, fn in walk_scoped(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        var: Optional[str] = None
        kinds: list[str] = []
        returned = False
        for stmt in _stmts_in_order(fn.body):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name) and \
                    isinstance(stmt.value, ast.Call) and \
                    (dotted(stmt.value.func) or "").split(".")[-1] == \
                    "Packet" and not stmt.value.args:
                var = stmt.targets[0].id
            for base, ks in _append_chains(stmt):
                if base == var:
                    kinds.extend(ks)
            if isinstance(stmt, ast.Return) and var is not None and \
                    isinstance(stmt.value, ast.Name) and \
                    stmt.value.id == var:
                returned = True
        if var is not None and returned:
            out[fn.name] = kinds
    return out


class _PackSite:
    __slots__ = ("msg", "kinds", "raw", "line")

    def __init__(self, msg: str, kinds: Optional[list[str]], raw: bool,
                 line: int) -> None:
        self.msg = msg
        self.kinds = kinds
        self.raw = raw
        self.line = line


def _pack_sites(mod: ParsedModule,
                helpers: dict[str, list[str]]) -> list[_PackSite]:
    sites: list[_PackSite] = []
    for _scope, fn in walk_scoped(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        tracked: dict[str, Optional[list[str]]] = {}  # None = raw-built
        for stmt in _stmts_in_order(fn.body):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name) and \
                    isinstance(stmt.value, ast.Call):
                tgt = stmt.targets[0].id
                callee = (dotted(stmt.value.func) or "").split(".")[-1]
                if callee == "Packet":
                    tracked[tgt] = [] if not stmt.value.args else None
                elif callee in helpers:
                    tracked[tgt] = list(helpers[callee])
            for base, ks in _append_chains(stmt):
                cur = tracked.get(base)
                if cur is not None:
                    cur.extend(ks)
            for node in _shallow_nodes(stmt):
                if not isinstance(node, ast.Call):
                    continue
                msg = next((m for a in node.args
                            if (m := _msgtype_name(a)) is not None), None)
                if msg is None:
                    continue
                attr = node.func.attr if isinstance(
                    node.func, ast.Attribute) else ""
                packet_arg: Optional[ast.expr] = None
                for a in node.args:
                    if _msgtype_name(a) is not None:
                        continue
                    if isinstance(a, ast.Name) and a.id in tracked:
                        packet_arg = a
                        break
                    if isinstance(a, ast.Call) and (
                            dotted(a.func) or "").split(".")[-1] == "Packet":
                        packet_arg = a
                        break
                if packet_arg is None:
                    if attr == "send_packet_raw":
                        sites.append(_PackSite(msg, None, True, node.lineno))
                    continue  # forwarding a received packet: not a pack site
                if isinstance(packet_arg, ast.Name):
                    kinds = tracked[packet_arg.id]
                    sites.append(_PackSite(
                        msg, list(kinds) if kinds is not None else None,
                        kinds is None, node.lineno))
                else:  # inline Packet(...) construction
                    if packet_arg.args:
                        sites.append(_PackSite(msg, None, True, node.lineno))
                    else:
                        sites.append(_PackSite(msg, [], False, node.lineno))
    return sites


# -- unpack-side extraction ---------------------------------------------------


def _handler_tables(mod: ParsedModule) -> dict[str, str]:
    """{method qualname: msgtype name} from class-level ``_HANDLERS``
    (or any ``*_HANDLERS``) dict literals mapping MsgType.X to methods."""
    out: dict[str, str] = {}
    for scope, node in walk_scoped(mod.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.endswith("_HANDLERS")
                and isinstance(node.value, ast.Dict)):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            msg = _msgtype_name(k) if k is not None else None
            vname = dotted(v) if v is not None else None
            if msg and vname:
                tail = vname.split(".")[-1]
                qual = f"{scope}.{tail}" if scope else tail
                out[qual] = msg
    return out


def _branch_test_msg(test: ast.expr) -> Optional[str]:
    """``msgtype == MsgType.X`` -> "X"; ``is_gate_redirect(msgtype)`` ->
    the redirect pseudo-type; anything else -> None."""
    if (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.ops[0], ast.Eq)):
        for side in (test.left, test.comparators[0]):
            msg = _msgtype_name(side)
            if msg is not None:
                return msg
    if isinstance(test, ast.Call):
        d = (dotted(test.func) or "").split(".")[-1]
        if d == "is_gate_redirect":
            return _REDIRECT_ANY
    return None


#: read item: (tag, msgtype-or-"" , varkey, kind).  kind "#rest" =
#: read_rest, "#bytes" = read_bytes, "#reset" = set_read_pos(0).
_ReadItem = tuple[str, str, str, str]


def _read_kind(node: ast.AST, packet_vars: set[str]) -> Optional[
        tuple[str, str]]:
    """(var, kind) when ``node`` is a cursor operation on a tracked
    packet var; kinds ``#rest``/``#bytes``/``#reset`` are markers."""
    from goworld_tpu.proto.schema import READ_TO_KIND

    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)):
        return None
    base = dotted(node.func.value)
    if base not in packet_vars:
        return None
    attr = node.func.attr
    if attr in READ_TO_KIND:
        return (base, READ_TO_KIND[attr])
    if attr == "read_rest":
        return (base, "#rest")
    if attr == "read_bytes":
        return (base, "#bytes")
    if attr == "set_read_pos":
        return (base, "#reset")
    return None


def _linear_reads(fn: ast.AST, packet_params: set[str],
                  module_defs: dict[str, ast.AST],
                  depth: int = 0) -> Optional[list[tuple[str, str]]]:
    """Branch-free read sequence [(varkey, kind)] of a helper, inlining
    one further level of same-module calls.  None when the helper
    branches on msgtype (it is then checked standalone, not inlined)."""
    out: list[tuple[str, str]] = []
    vars_ = set(packet_params)
    for stmt in _stmts_in_order(fn.body):
        if isinstance(stmt, ast.If) and _branch_test_msg(stmt.test):
            return None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                isinstance(stmt.value, ast.Call) and \
                (dotted(stmt.value.func) or "").split(".")[-1] == \
                "Packet" and any(
                    isinstance(n, ast.Name) and n.id in vars_
                    for n in ast.walk(stmt.value)):
            vars_.add(stmt.targets[0].id)
        for node in _shallow_nodes(stmt):
            got = _read_kind(node, vars_)
            if got is not None:
                out.append(got)
                continue
            if depth == 0 and isinstance(node, ast.Call):
                out.extend(_maybe_inline(node, vars_, module_defs, depth))
    return out


def _maybe_inline(node: ast.Call, packet_vars: set[str],
                  module_defs: dict[str, ast.AST],
                  depth: int) -> list[tuple[str, str]]:
    """Reads a same-module helper performs on a packet passed to it,
    re-keyed onto the caller's variable."""
    tail = (dotted(node.func) or "").split(".")[-1]
    target = module_defs.get(tail)
    if target is None:
        return []
    pos = next((i for i, a in enumerate(node.args)
                if isinstance(a, ast.Name) and a.id in packet_vars), None)
    if pos is None:
        return []
    arg = node.args[pos]
    assert isinstance(arg, ast.Name)
    params = [a.arg for a in _all_args(target)]
    if params and params[0] in ("self", "cls"):
        params = params[1:]
    if pos >= len(params):
        return []
    sub = _linear_reads(target, {params[pos]}, module_defs, depth + 1)
    if sub is None:
        return []
    return [(arg.id, kind) for _var, kind in sub]


def _unpack_sequences(mod: ParsedModule) -> list[tuple[str, str, int,
                                                       list[list[str]]]]:
    """Per checked function: (msgtype name, symbol, line, read segments).

    A function contributes when it appears in a ``*_HANDLERS`` table (its
    whole body reads that one msgtype) and/or contains ``msgtype ==
    MsgType.X`` branches (reads inside the branch attribute to X; reads
    outside attribute to every msgtype the function handles).  Segments
    split on ``set_read_pos(0)`` and on peek-vars built via
    ``Packet(packet.payload)``; each is prefix-checked from offset 0."""
    tables = _handler_tables(mod)
    module_defs: dict[str, ast.AST] = {}
    for _scope, node in walk_scoped(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            module_defs.setdefault(node.name, node)

    results: list[tuple[str, str, int, list[list[str]]]] = []
    for scope, fn in walk_scoped(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        qual = f"{scope}.{fn.name}" if scope else fn.name
        table_msg = tables.get(qual)
        packet_params = {a.arg for a in _all_args(fn)
                         if a.arg in ("packet", "pkt")}
        if not packet_params:
            continue

        items: list[_ReadItem] = []
        vars_ = set(packet_params)
        branch_msgs: list[str] = []

        def emit(node: ast.AST, branch: str) -> None:
            got = _read_kind(node, vars_)
            if got is not None:
                items.append((branch, "", got[0], got[1]))
                return
            if isinstance(node, ast.Call):
                for var, kind in _maybe_inline(node, vars_,
                                               module_defs, 0):
                    items.append((branch, "", var, kind))

        def collect(body: list[ast.stmt], branch: str) -> None:
            for stmt in body:
                if isinstance(stmt, ast.If):
                    msg = _branch_test_msg(stmt.test)
                    if msg is not None:
                        if msg not in branch_msgs:
                            branch_msgs.append(msg)
                        collect(stmt.body, msg)
                        collect(stmt.orelse, branch)
                        continue
                    # non-msgtype If: reads in the TEST run on this path
                    for node in ast.walk(stmt.test):
                        emit(node, branch)
                    collect(stmt.body, branch)
                    collect(stmt.orelse, branch)
                    continue
                if isinstance(stmt, ast.Assign) and \
                        len(stmt.targets) == 1 and \
                        isinstance(stmt.targets[0], ast.Name) and \
                        isinstance(stmt.value, ast.Call) and \
                        (dotted(stmt.value.func) or ""
                         ).split(".")[-1] == "Packet" and any(
                            isinstance(n, ast.Name) and n.id in vars_
                            for n in ast.walk(stmt.value)):
                    vars_.add(stmt.targets[0].id)
                for node in _shallow_nodes(stmt):
                    emit(node, branch)
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if isinstance(sub, list) and sub and isinstance(
                            sub[0], ast.stmt):
                        collect(sub, branch)
                for h in getattr(stmt, "handlers", []):
                    collect(h.body, branch)

        collect(fn.body, "")

        targets = list(branch_msgs)
        if table_msg is not None and table_msg not in targets:
            targets.insert(0, table_msg)
        if not targets:
            continue
        for msg in targets:
            segments: list[list[str]] = []
            seg_of: dict[str, list[str]] = {}
            for branch, _x, var, kind in items:
                if branch not in ("", msg):
                    continue
                if kind == "#reset":
                    seg_of.pop(var, None)
                    continue
                seg = seg_of.get(var)
                if seg is None:
                    seg = seg_of[var] = []
                    segments.append(seg)
                seg.append(kind)
            results.append((msg, qual, fn.lineno, segments))
    return results


def check_r7(modules: list[ParsedModule], root: str) -> list[Violation]:
    from goworld_tpu.proto import schema as engine_schema

    parsed = _parse_schema_table(modules)
    if parsed is None:
        return []
    table, schema_mod = parsed
    out: list[Violation] = []

    # 1. every MsgType member has a schema
    for name, value in sorted(table.types.items()):
        if name not in table.entries:
            out.append(schema_mod.violation(
                "R7", 1,
                f"MsgType.{name} ({value}) has no wire schema — declare "
                f"its payload layout in proto/schema.py"))

    # 2. digest pin: the layout table must match SCHEMA_HISTORY for the
    # CURRENT version — layout edits land as (bump, new digest) pairs.
    if table.version is not None:
        digest = engine_schema.digest_of(
            table.version,
            [(e.name, e.value, e.kinds, e.raw)
             for e in table.entries.values()],
            table.trailer)
        pinned = table.history.get(table.version)
        if pinned is None:
            out.append(schema_mod.violation(
                "R7", table.history_line,
                f"SCHEMA_HISTORY has no digest for PROTO_VERSION "
                f"{table.version} — append the pair (and keep earlier "
                f"entries)"))
        elif pinned != digest:
            out.append(schema_mod.violation(
                "R7", table.history_line,
                f"wire-schema digest {digest} does not match the pinned "
                f"{pinned} for PROTO_VERSION {table.version} — a payload "
                f"layout changed: bump PROTO_VERSION in proto/msgtypes.py "
                f"and append the new (version, digest) pair to "
                f"SCHEMA_HISTORY"))

    # 3. pack sites across the whole package
    packed: set[str] = set()
    for mod in modules:
        if mod.path == _SCHEMA_PATH:
            continue
        helpers = _packet_helpers(mod)
        for site in _pack_sites(mod, helpers):
            sch = table.entries.get(site.msg)
            if sch is None:
                if site.msg in table.types:
                    out.append(mod.violation(
                        "R7", site.line,
                        f"packs MsgType.{site.msg} which has no wire "
                        f"schema in proto/schema.py"))
                continue
            packed.add(site.msg)
            if site.raw:
                if sch.raw is None and sch.kinds:
                    out.append(mod.violation(
                        "R7", site.line,
                        f"MsgType.{site.msg} is sent as a raw payload but "
                        f"its schema declares fields {sch.kinds} — build "
                        f"it with the typed appends or declare a raw "
                        f"region"))
                continue
            kinds = list(site.kinds or [])
            expect = list(sch.kinds)
            if kinds and kinds[-1] == "#raw":
                if sch.raw is None:
                    out.append(mod.violation(
                        "R7", site.line,
                        f"MsgType.{site.msg}: trailing append_bytes but "
                        f"the schema declares no raw region"))
                    continue
                kinds = kinds[:-1]
            ok = kinds == expect or (
                sch.gate_appended
                and kinds == expect[:len(expect) - sch.gate_appended])
            if not ok:
                out.append(mod.violation(
                    "R7", site.line,
                    f"MsgType.{site.msg} packed as {kinds} but the wire "
                    f"schema declares {expect} — sender/receiver drift; "
                    f"fix the site or update proto/schema.py (and bump "
                    f"PROTO_VERSION)"))

    # 4. schema coverage: a declared layout nobody packs is drift too
    for name, e in sorted(table.entries.items()):
        if name not in packed and name in table.types:
            out.append(schema_mod.violation(
                "R7", e.line,
                f"MsgType.{name} has a declared schema but no pack site "
                f"anywhere in the package — dead layout or a sender the "
                f"extractor cannot see (baseline with a reason if so)"))

    # 5. handler-side reads in dispatcher/gate/game/rebalance
    redirect_prefix = ["u16", "cid"]
    for mod in modules:
        if not mod.path.startswith(_R7_UNPACK_PREFIXES):
            continue
        for msg, qual, line, segments in _unpack_sequences(mod):
            if msg == _REDIRECT_ANY:
                expect, raw = redirect_prefix, "redirect-payload"
            else:
                sch = table.entries.get(msg)
                if sch is None:
                    if msg in table.types:
                        out.append(mod.violation(
                            "R7", line,
                            f"handles MsgType.{msg} which has no wire "
                            f"schema in proto/schema.py"))
                    continue
                expect, raw = list(sch.kinds), sch.raw
            for seg in segments:
                err = _match_read_segment(seg, expect, raw)
                if err:
                    out.append(mod.violation(
                        "R7", line,
                        f"{qual} reads MsgType.{msg} as {seg} but the "
                        f"wire schema declares {expect}"
                        f"{' + raw ' + raw if raw else ''} — {err}"))
    return out


def _match_read_segment(seg: list[str], expect: list[str],
                        raw: Optional[str]) -> Optional[str]:
    """A read segment must consume declared fields in order from offset 0
    (stopping early is fine; ``read_rest`` swallows the remainder)."""
    i = 0
    for kind in seg:
        if kind == "#rest":
            return None
        if i >= len(expect):
            if raw and kind == "#bytes":
                continue
            return (f"position {i} reads past the declared layout")
        if kind == "#bytes":
            return (f"position {i}: fixed read_bytes over a structured "
                    f"field {expect[i]!r}")
        if kind != expect[i]:
            return (f"position {i} expects {expect[i]!r}, handler reads "
                    f"{kind!r}")
        i += 1
    return None


CHECKERS = {
    "R1": check_r1,
    "R2": check_r2,
    "R3": check_r3,
    "R4": check_r4,
    "R5": check_r5,
    "R6": check_r6,
    "R7": check_r7,
}

"""Explicit-state model checker for the cluster protocol.

A compact Python model of the dispatcher<->game<->gate state machines —
client-binding generations, migrate target states (connected / blocked /
UNKNOWN / declared-DEAD), reconnect-grace windows, pending-sync parking,
buffered boots — explored EXHAUSTIVELY over bounded interleavings of
message delivery, process crash / cold restart, and grace expiry.  The
transition rules mirror the shipped code path by path (each cites its
``file:line``), so the model is the SPEC: the next protocol PR extends
the model first and lands against these invariants instead of against
production.

Invariants (the PR-9 zero-loss contract, asserted in every reached state
and at every quiescent terminal state):

- **I1 no lost / duplicate entity** — an entity has exactly one live
  copy across games, in-flight ``REAL_MIGRATE`` payloads, and dispatcher
  grace buffers; a copy count of zero is legal only after the process
  HOSTING the copy (or holding it on a dying socket) crashed.
- **I2 no stale sync delivery** — a position-sync record is never
  delivered to a game that does not host its entity (parking + FIFO
  flush-behind-``REAL_MIGRATE`` is what guarantees it).
- **I3 no stuck terminal** — when no action remains, the entity lives on
  a live game (unless crash-lost), nothing sits in a buffer forever, and
  every boot request was served unless its only game stayed dead.
- **I4 generation-scoped detach** — a gate-restart detach broadcast
  never removes a binding of the valid (new) generation, under any
  cross-dispatcher delivery order.

Scope honesty: the exploration is BOUNDED (budgets below) and the model
abstracts time into nondeterministic grace-expiry events — it proves the
protocol LOGIC under every interleaving within the bounds, not liveness
under real clocks, and not payload encoding (gwlint R7 owns layout).

``python -m goworld_tpu.analysis.modelcheck`` runs the tier-1 configs
and reports deterministic state counts (tools/lint.sh wires it in).

Seeded mutants (``mutants=`` on a config) flip one protocol rule each;
tests/test_modelcheck.py proves every one is caught — the checker has
teeth, not just green lights.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Iterable, NamedTuple, Optional

Msg = tuple[str, ...]
Chan = tuple[Msg, ...]

#: Known mutant switches (test_modelcheck pins each one caught).
MUTANTS = (
    "no_bounce",          # dead-target REAL_MIGRATE dropped, not bounced home
    "no_purge_cold_boot",  # cold handshake keeps the dead incarnation's routes
    "infinite_grace",     # reconnect-grace windows never expire
    "no_sync_parking",    # syncs for a blocked (migrating) entity route anyway
    "skip_gen_check",     # gate-restart detach ignores the valid generation
    "drop_boot_no_game",  # boot with no connected game dropped, not buffered
    # -- space-migration rules (SpaceMigrateModel) --
    "no_space_bounce",    # dead-target SPACE_MIGRATE_DATA dropped, not bounced
    "no_space_park",      # PREPARE skips parking the members' streams
    "no_freeze_cancel_member",  # freeze keeps members' pending entity migrates
    "no_unfreeze_on_abort",     # abort leaves the space FROZEN forever
    "no_frozen_join_guard",     # a join lands in a FROZEN space instead of queueing
)


# --- framework ---------------------------------------------------------------


class Step(NamedTuple):
    label: str
    state: "State"
    violations: tuple[str, ...] = ()


State = tuple  # models return hashable NamedTuples (subtypes of tuple)


class Model:
    """Interface an explorable protocol model implements."""

    name = "model"

    def initial(self) -> State:
        raise NotImplementedError

    def actions(self, s: State) -> list[Step]:
        raise NotImplementedError

    def state_invariants(self, s: State) -> tuple[str, ...]:
        return ()

    def terminal_violations(self, s: State) -> tuple[str, ...]:
        return ()


@dataclasses.dataclass
class Counterexample:
    message: str
    trace: tuple[str, ...]

    def render(self) -> str:
        lines = [f"violation: {self.message}", "  trace:"]
        lines += [f"    {i + 1:2d}. {step}"
                  for i, step in enumerate(self.trace)]
        return "\n".join(lines)


@dataclasses.dataclass
class CheckResult:
    model: str
    states: int
    transitions: int
    terminals: int
    violations: list[Counterexample]

    @property
    def ok(self) -> bool:
        return not self.violations

    def render(self) -> str:
        head = (f"{self.model}: {self.states} states, "
                f"{self.transitions} transitions, {self.terminals} "
                f"terminal state(s), {len(self.violations)} violation(s)")
        return "\n".join([head] + [v.render() for v in self.violations])


def explore(model: Model, max_states: int = 1_000_000,
            max_counterexamples: int = 8) -> CheckResult:
    """Exhaustive BFS over the model's reachable states.  Deterministic:
    identical models explore identical state counts in identical order
    (actions are returned in rule order; the frontier is FIFO)."""
    init = model.initial()
    parents: dict[State, Optional[tuple[State, str]]] = {init: None}
    frontier: deque[State] = deque([init])
    violations: list[Counterexample] = []
    transitions = 0
    terminals = 0

    def trace_to(s: State, last: Optional[str] = None) -> tuple[str, ...]:
        labels: list[str] = [] if last is None else [last]
        cur: Optional[tuple[State, str]] = parents[s]
        while cur is not None:
            labels.append(cur[1])
            cur = parents[cur[0]]
        return tuple(reversed(labels))

    def report(msg: str, s: State, last: Optional[str] = None) -> None:
        if len(violations) < max_counterexamples:
            violations.append(Counterexample(msg, trace_to(s, last)))

    for msg in model.state_invariants(init):
        report(msg, init)
    while frontier:
        if len(parents) > max_states:
            raise RuntimeError(
                f"{model.name}: state space exceeded {max_states} — "
                f"tighten the config bounds")
        s = frontier.popleft()
        steps = model.actions(s)
        if not steps:
            terminals += 1
            for msg in model.terminal_violations(s):
                report(msg, s)
            continue
        for label, nxt, viols in steps:
            transitions += 1
            for msg in viols:
                report(msg, s, label)
            if nxt not in parents:
                parents[nxt] = (s, label)
                frontier.append(nxt)
                for msg in model.state_invariants(nxt):
                    report(msg, nxt)
    return CheckResult(model.name, len(parents), transitions, terminals,
                       violations)


# --- the migrate + crash model ----------------------------------------------
#
# One entity "E" on game 1, one dispatcher, one migration toward game 2.
# Game indices are 0-based internally, 1-based in labels.  Each rule
# cites the code it mirrors.

LINK_CONN = "conn"
LINK_GRACE = "grace"
LINK_UNREG = "unreg"
LINK_DEAD = "dead"

M_MREQ = ("MIGRATE_REQUEST",)
M_MACK = ("MIGRATE_REQUEST_ACK",)
M_RMIG = ("REAL_MIGRATE",)
M_SYNC = ("SYNC_POSITION",)
M_CANCEL = ("CANCEL_MIGRATE",)
M_CREATE = ("NOTIFY_CREATE_ENTITY",)
M_HSHAKE_COLD = ("SET_GAME_ID", "cold")


class MigState(NamedTuple):
    g_alive: tuple[bool, bool]
    g_has_e: tuple[bool, bool]
    g1_migrate: str       # idle | requested | sent | cancelled | closed
    links: tuple[str, str]
    route: int            # 0 unrouted, 1, 2
    blocked: bool         # dispatcher migrate window for E
    parked: Chan          # per-entity pending queue (parked syncs)
    gpending: tuple[Chan, Chan]   # per-game grace buffers
    to_g: tuple[Chan, Chan]       # dispatcher -> game FIFOs
    from_g: tuple[Chan, Chan]     # game -> dispatcher FIFOs
    crashes_left: int
    restarts_left: int
    syncs_left: int
    cancels_left: int
    migrates_left: int
    crash_lost: bool


def _put(chans: tuple[Chan, Chan], i: int, *msgs: Msg
         ) -> tuple[Chan, Chan]:
    out = list(chans)
    out[i] = out[i] + tuple(msgs)
    return (out[0], out[1])


def _pop(chans: tuple[Chan, Chan], i: int) -> tuple[Msg, tuple[Chan, Chan]]:
    out = list(chans)
    head, out[i] = out[i][0], out[i][1:]
    return head, (out[0], out[1])


@dataclasses.dataclass(frozen=True)
class MigConfig:
    name: str = "migrate_crash"
    crashes: int = 1          # crash budget for game 2 (the target)
    restarts: int = 1         # cold-restart budget for game 2
    syncs: int = 1            # position-sync records injected at D
    cancels: int = 1          # migrator deadline-cancel budget
    migrates: int = 1
    target_unregistered: bool = False  # UNKNOWN-target start (replayed
    #                                    RMIG racing a re-handshake)
    mutants: frozenset[str] = frozenset()


class MigrateCrashModel(Model):
    """dispatcher/service.py + rebalance/migrator.py + entity manager
    notify flow, reduced to E's fate under every interleaving."""

    def __init__(self, cfg: MigConfig) -> None:
        bad = cfg.mutants - set(MUTANTS)
        if bad:
            raise ValueError(f"unknown mutants {sorted(bad)}")
        self.cfg = cfg
        self.name = cfg.name

    def initial(self) -> MigState:
        cfg = self.cfg
        return MigState(
            g_alive=(True, True),
            g_has_e=(True, False),
            g1_migrate="idle",
            links=(LINK_CONN,
                   LINK_UNREG if cfg.target_unregistered else LINK_CONN),
            route=1,
            blocked=False,
            parked=(),
            gpending=((), ()),
            to_g=((), ()),
            from_g=((), ()),
            crashes_left=cfg.crashes,
            restarts_left=cfg.restarts,
            syncs_left=cfg.syncs,
            cancels_left=cfg.cancels,
            migrates_left=cfg.migrates,
            crash_lost=False,
        )

    # -- shared sub-rules ---------------------------------------------------

    def _deliver_to_game(self, s: MigState, gi: int, msg: Msg
                         ) -> MigState:
        """_GameInfo.dispatch (dispatcher/service.py:116-122): connected
        sends, a grace/unreg window buffers, a dead game drops."""
        link = s.links[gi]
        if link == LINK_CONN:
            return s._replace(to_g=_put(s.to_g, gi, msg))
        if link in (LINK_GRACE, LINK_UNREG):
            return s._replace(gpending=_put(s.gpending, gi, msg))
        return s  # dead: drop (syncs/acks only ever reach here)

    def _flush_parked(self, s: MigState, gi: int) -> MigState:
        """_flush_entity_pending (dispatcher/service.py:774-779): parked
        packets follow E to wherever it routed, AFTER the REAL_MIGRATE on
        the same FIFO."""
        out = s
        for msg in s.parked:
            out = self._deliver_to_game(out, gi, msg)
        return out._replace(parked=(), blocked=False)

    # -- actions ------------------------------------------------------------

    def actions(self, st: State) -> list[Step]:
        assert isinstance(st, MigState)
        s = st
        cfg = self.cfg
        steps: list[Step] = []

        # migrator issues the move (rebalance/migrator.py:81-99 ->
        # entity.enter_space -> MIGRATE_REQUEST, entity.py:750-765)
        if (s.migrates_left and s.g1_migrate == "idle" and s.g_alive[0]
                and s.g_has_e[0]):
            steps.append(Step(
                "game1: send MIGRATE_REQUEST(E)",
                s._replace(g1_migrate="requested",
                           migrates_left=s.migrates_left - 1,
                           from_g=_put(s.from_g, 0, M_MREQ))))

        # migrator deadline fires (rebalance/migrator.py:143-150 ->
        # cancel_enter_space -> CANCEL_MIGRATE; the entity stays)
        if s.cancels_left and s.g1_migrate == "requested":
            steps.append(Step(
                "game1: migrate deadline -> CANCEL_MIGRATE(E)",
                s._replace(g1_migrate="cancelled",
                           cancels_left=s.cancels_left - 1,
                           from_g=_put(s.from_g, 0, M_CANCEL))))

        # a gate-side sync record reaches the dispatcher
        # (dispatcher/service.py:1222-1290)
        if s.syncs_left:
            nxt = s._replace(syncs_left=s.syncs_left - 1)
            if s.blocked and "no_sync_parking" not in cfg.mutants:
                # park with the entity's pending queue (:1246-1254)
                nxt = nxt._replace(parked=nxt.parked + (M_SYNC,))
            elif s.route == 0:
                # unrouted grace buffer (:757-767)
                nxt = nxt._replace(parked=nxt.parked + (M_SYNC,))
            else:
                nxt = self._deliver_to_game(nxt, s.route - 1, M_SYNC)
            steps.append(Step("gate: SYNC(E) reaches dispatcher", nxt))

        # deliver game -> dispatcher
        for gi in (0, 1):
            if not s.from_g[gi]:
                continue
            msg, from_g = _pop(s.from_g, gi)
            base = s._replace(from_g=from_g)
            steps.append(self._dispatcher_handle(base, gi, msg))

        # deliver dispatcher -> game
        for gi in (0, 1):
            if not s.to_g[gi]:
                continue
            msg, to_g = _pop(s.to_g, gi)
            base = s._replace(to_g=to_g)
            steps.append(self._game_handle(base, gi, msg))

        # crash game 2 (the migrate target)
        if s.crashes_left and s.g_alive[1]:
            lost = s.g_has_e[1] or any(
                m == M_RMIG for m in s.to_g[1])  # on a dying socket
            nxt = s._replace(
                g_alive=(s.g_alive[0], False),
                g_has_e=(s.g_has_e[0], False),
                crashes_left=s.crashes_left - 1,
                to_g=(s.to_g[0], ()),
                from_g=(s.from_g[0], ()),
                links=(s.links[0],
                       LINK_GRACE if s.links[1] == LINK_CONN
                       else s.links[1]),
                crash_lost=s.crash_lost or lost)
            steps.append(Step("game2: CRASH", nxt))

        # cold restart of game 2 (fresh process, empty entity set)
        if s.restarts_left and not s.g_alive[1]:
            steps.append(Step(
                "game2: cold restart -> SET_GAME_ID(cold)",
                s._replace(g_alive=(s.g_alive[0], True),
                           restarts_left=s.restarts_left - 1,
                           from_g=_put(s.from_g, 1, M_HSHAKE_COLD))))

        # an unregistered-but-alive target finally handshakes
        # (the replayed-RMIG-races-rehandshake scenario, PR 9)
        if (s.g_alive[1] and s.links[1] == LINK_UNREG
                and M_HSHAKE_COLD not in s.from_g[1]):
            steps.append(Step(
                "game2: handshake SET_GAME_ID(cold)",
                s._replace(from_g=_put(s.from_g, 1, M_HSHAKE_COLD))))

        # reconnect-grace expiry on game 2 — the sweep fires on wall
        # clock whether or not the process is back up, including the
        # alive-but-slow-to-handshake UNKNOWN-target window
        # (_sweep_dead_frozen_games:649-676 + _handle_game_down:1410-1424)
        if s.links[1] == LINK_GRACE and \
                "infinite_grace" not in cfg.mutants:
            steps.append(self._expire_game2(s))

        # unrouted-entity sweep drops parked packets for an entity no
        # game claimed (_sweep_unrouted_entities:698-715).  The window is
        # long (seconds) against an in-flight NOTIFY_CREATE (one RTT), so
        # the time-free model does not race the sweep against a CREATE
        # already on the wire.
        if (s.route == 0 and s.parked and not s.blocked
                and not any(M_CREATE in c for c in s.from_g)):
            steps.append(Step(
                "dispatcher: unrouted sweep drops parked packets",
                s._replace(parked=())))

        return steps

    def _dispatcher_handle(self, s: MigState, gi: int, msg: Msg) -> Step:
        g = f"game{gi + 1}"
        cfg = self.cfg
        viols: tuple[str, ...] = ()
        if msg == M_MREQ:
            # block E's stream, ack through the buffered path
            # (_handle_migrate_request:1122-1134)
            nxt = self._deliver_to_game(
                s._replace(blocked=True), 0, M_MACK)
            return Step(f"dispatcher: {g} MIGRATE_REQUEST -> block E, "
                        f"ack", nxt)
        if msg == M_CANCEL:
            # unblock + flush parked to E's current route
            # (_handle_cancel_migrate:1212-1218)
            nxt = s
            if s.route:
                nxt = self._flush_parked(s, s.route - 1)
            nxt = nxt._replace(blocked=False)
            return Step(f"dispatcher: {g} CANCEL_MIGRATE -> unblock E",
                        nxt)
        if msg == M_CREATE:
            # route E here, flush parked (_handle_notify_create_entity)
            nxt = self._flush_parked(s._replace(route=gi + 1), gi)
            return Step(f"dispatcher: {g} NOTIFY_CREATE -> route E", nxt)
        if msg == M_RMIG:
            return self._route_real_migrate(s)
        if msg == M_HSHAKE_COLD:
            # cold boot: purge the dead incarnation's routes, then flush
            # the grace buffer to the fresh process
            # (_handle_set_game_id:857-874 purge, 910 unblock_and_flush)
            nxt = s
            if nxt.route == gi + 1 and \
                    "no_purge_cold_boot" not in cfg.mutants:
                nxt = nxt._replace(route=0)
            links = list(nxt.links)
            links[gi] = LINK_CONN
            gp = list(nxt.gpending)
            flushed = gp[gi]
            gp[gi] = ()
            nxt = nxt._replace(
                links=(links[0], links[1]),
                gpending=(gp[0], gp[1]),
                to_g=_put(nxt.to_g, gi, *flushed))
            return Step(f"dispatcher: {g} cold handshake -> purge stale "
                        f"routes, flush {len(flushed)} buffered", nxt,
                        viols)
        raise AssertionError(f"unmodeled dispatcher message {msg}")

    def _route_real_migrate(self, s: MigState) -> Step:
        """_handle_real_migrate (dispatcher/service.py:1146-1192): route,
        buffer behind a grace window, or bounce the payload HOME — never
        drop the entity's last copy."""
        cfg = self.cfg
        tlink = s.links[1]
        if tlink == LINK_UNREG:
            # unknown target: grant the standard reconnect-grace window
            # and buffer (:1169-1176)
            nxt = s._replace(
                links=(s.links[0], LINK_GRACE), route=2,
                gpending=_put(s.gpending, 1, M_RMIG))
            nxt = self._flush_parked(nxt, 1)
            return Step("dispatcher: REAL_MIGRATE(E) -> unknown game2, "
                        "buffer behind grace window", nxt)
        if tlink in (LINK_CONN, LINK_GRACE):
            nxt = self._deliver_to_game(s._replace(route=2), 1, M_RMIG)
            nxt = self._flush_parked(nxt, 1)
            return Step("dispatcher: REAL_MIGRATE(E) -> route to game2",
                        nxt)
        # declared dead: bounce home (:1177-1192)
        if "no_bounce" in cfg.mutants:
            nxt = s._replace(route=0, blocked=False, parked=())
            return Step("dispatcher: REAL_MIGRATE(E) -> target dead, "
                        "payload DROPPED [mutant]", nxt,
                        ("entity E's last copy dropped at the "
                         "dispatcher (dead target, no bounce)",))
        if s.links[0] in (LINK_CONN, LINK_GRACE):
            nxt = self._deliver_to_game(s._replace(route=1), 0, M_RMIG)
            nxt = self._flush_parked(nxt, 0)
            return Step("dispatcher: REAL_MIGRATE(E) -> target dead, "
                        "bounce HOME to game1", nxt)
        # both ends gone: only reachable with a game-1 crash in budget
        nxt = s._replace(route=0, blocked=False, parked=(),
                         crash_lost=True)
        return Step("dispatcher: REAL_MIGRATE(E) -> both ends crashed; "
                    "state dropped", nxt)

    def _expire_game2(self, s: MigState) -> Step:
        """Grace lapse: bounce buffered REAL_MIGRATEs home, drop the
        rest, declare the game down (purging its routes)."""
        nxt = s
        viols: list[str] = []
        for msg in s.gpending[1]:
            if msg != M_RMIG:
                continue  # parked syncs etc. drop with the window
            if "no_bounce" in self.cfg.mutants:
                viols.append("entity E's last copy dropped at grace "
                             "expiry (no bounce)")
                nxt = nxt._replace(route=0, blocked=False, parked=())
            elif nxt.links[0] in (LINK_CONN, LINK_GRACE):
                nxt = self._deliver_to_game(
                    nxt._replace(route=1), 0, M_RMIG)
                nxt = self._flush_parked(nxt, 0)
            else:
                nxt = nxt._replace(route=0, crash_lost=True)
        nxt = nxt._replace(gpending=(nxt.gpending[0], ()),
                           links=(nxt.links[0], LINK_DEAD))
        if nxt.route == 2:  # _handle_game_down purges dead routes
            nxt = nxt._replace(route=0)
        return Step("dispatcher: game2 grace window expires -> declared "
                    "dead", nxt, tuple(viols))

    def _game_handle(self, s: MigState, gi: int, msg: Msg) -> Step:
        g = f"game{gi + 1}"
        if msg == M_MACK:
            # entity.py:803-847: pack state, send REAL_MIGRATE, destroy
            # the local copy.  A cancelled request ignores the stale ack.
            if gi == 0 and s.g1_migrate == "requested":
                nxt = s._replace(
                    g_has_e=(False, s.g_has_e[1]), g1_migrate="sent",
                    from_g=_put(s.from_g, 0, M_RMIG))
                return Step(f"{g}: MIGRATE_REQUEST_ACK -> send "
                            f"REAL_MIGRATE(E), drop local copy", nxt)
            return Step(f"{g}: stale MIGRATE_REQUEST_ACK ignored", s)
        if msg == M_RMIG:
            # game/service.py:712-725 restore_entity + the entity
            # manager's NOTIFY_CREATE_ENTITY (entity_manager.py:503)
            has = list(s.g_has_e)
            has[gi] = True
            mig = "closed" if gi == 0 else s.g1_migrate
            nxt = s._replace(g_has_e=(has[0], has[1]), g1_migrate=mig,
                             from_g=_put(s.from_g, gi, M_CREATE))
            kind = "bounced home" if gi == 0 else "arrives"
            return Step(f"{g}: REAL_MIGRATE(E) {kind} -> restore, "
                        f"NOTIFY_CREATE", nxt)
        if msg == M_SYNC:
            # The PR-9 parking clause: a record must never reach a game
            # OTHER than the one holding E's live copy.  A record for an
            # entity with no live copy anywhere (crash-lost) is dropped
            # by ``get_entity -> None`` (game/service.py:667-670) — a
            # legal drop, not a mis-route.
            viols2: tuple[str, ...] = ()
            if not s.g_has_e[gi] and self._copies(s) >= 1:
                viols2 = (f"sync record for E delivered to {g} while E's "
                          f"live copy is elsewhere (stale-game delivery)",)
            return Step(f"{g}: SYNC(E) delivered", s, viols2)
        raise AssertionError(f"unmodeled game message {msg}")

    # -- invariants ---------------------------------------------------------

    def _copies(self, s: MigState) -> int:
        chans: Iterable[Chan] = (*s.to_g, *s.from_g, *s.gpending)
        in_flight = sum(1 for c in chans for m in c if m == M_RMIG)
        return int(s.g_has_e[0]) + int(s.g_has_e[1]) + in_flight

    def state_invariants(self, st: State) -> tuple[str, ...]:
        assert isinstance(st, MigState)
        s = st
        out: list[str] = []
        copies = self._copies(s)
        if copies > 1:
            out.append(f"entity E duplicated: {copies} live copies")
        if copies == 0 and not s.crash_lost:
            out.append("entity E vanished with no crash to blame")
        return tuple(out)

    def terminal_violations(self, st: State) -> tuple[str, ...]:
        assert isinstance(st, MigState)
        s = st
        out: list[str] = []
        hosted_alive = any(s.g_has_e[i] and s.g_alive[i] for i in (0, 1))
        if not hosted_alive and not s.crash_lost:
            out.append("terminal state: E is not hosted by any live game")
        if s.route and not s.g_has_e[s.route - 1]:
            # Route hygiene: the entity table must never keep an entry
            # pointing at a game that does not host the entity — the
            # cold-boot purge (_handle_set_game_id:857-874) and the
            # game-down sweep (_handle_game_down:1410-1424) exist
            # precisely to keep this true.
            out.append(f"terminal state: stale routing-table entry — E "
                       f"routed to game{s.route} which does not host it")
        if any(M_RMIG in gp for gp in s.gpending):
            out.append("terminal state: REAL_MIGRATE(E) stuck in a "
                       "dispatcher buffer forever")
        if s.blocked and all(s.g_alive):
            out.append("terminal state: E's stream blocked forever with "
                       "both games alive")
        return tuple(out)


# --- the gate-generation model ----------------------------------------------


class GateGenState(NamedTuple):
    bindings: frozenset[tuple[str, int]]  # (clientid, gate generation)
    detach_chan: Chan   # dispatcher A -> game (the restart broadcast)
    connect_chan: Chan  # dispatcher B -> game (the new client's boot)
    c2_bound: bool


@dataclasses.dataclass(frozen=True)
class GateGenConfig:
    name: str = "gate_generation"
    valid_gen: int = 2
    mutants: frozenset[str] = frozenset()


class GateGenerationModel(Model):
    """A gate process restarts: its detach broadcast (naming the new
    generation as valid) races the new generation's first client boot on
    a DIFFERENT dispatcher link — the PR 9 cross-dispatcher ordering.
    Mirrors entity/game_client.py gate_gen + entity_manager
    .on_gate_disconnected(gateid, valid_gen)."""

    def __init__(self, cfg: GateGenConfig) -> None:
        self.cfg = cfg
        self.name = cfg.name

    def initial(self) -> GateGenState:
        return GateGenState(
            bindings=frozenset({("c1", 1)}),
            detach_chan=(("NOTIFY_GATE_DISCONNECTED",
                          str(self.cfg.valid_gen)),),
            connect_chan=(("NOTIFY_CLIENT_CONNECTED", "c2",
                           str(self.cfg.valid_gen)),),
            c2_bound=False,
        )

    def actions(self, st: State) -> list[Step]:
        assert isinstance(st, GateGenState)
        s = st
        steps: list[Step] = []
        if s.detach_chan:
            msg, rest = s.detach_chan[0], s.detach_chan[1:]
            valid = int(msg[1])
            viols: list[str] = []
            if "skip_gen_check" in self.cfg.mutants:
                dropped = s.bindings
            else:
                dropped = frozenset(b for b in s.bindings
                                    if b[1] != valid)
            for cid, gen in dropped:
                if gen == valid:
                    viols.append(
                        f"detach broadcast removed live binding "
                        f"({cid}, gen {gen}) of the VALID generation")
            steps.append(Step(
                f"game: detach gate bindings (valid gen {valid})",
                s._replace(bindings=s.bindings - dropped,
                           detach_chan=rest),
                tuple(viols)))
        if s.connect_chan:
            msg, rest = s.connect_chan[0], s.connect_chan[1:]
            cid, gen = msg[1], int(msg[2])
            steps.append(Step(
                f"game: bind client {cid} (gen {gen})",
                s._replace(bindings=s.bindings | {(cid, gen)},
                           connect_chan=rest, c2_bound=True)))
        return steps

    def terminal_violations(self, st: State) -> tuple[str, ...]:
        assert isinstance(st, GateGenState)
        s = st
        out: list[str] = []
        if ("c1", 1) in s.bindings:
            out.append("dead-generation binding (c1, gen 1) survived "
                       "the restart detach")
        if s.c2_bound and ("c2", self.cfg.valid_gen) not in s.bindings:
            out.append("valid-generation binding (c2) was detached")
        return tuple(out)


# --- the boot-during-link-flap model -----------------------------------------


class BootState(NamedTuple):
    link: str   # conn | grace | dead
    boot: str   # pending | buffered | served | dropped
    reconnects_left: int


@dataclasses.dataclass(frozen=True)
class BootConfig:
    name: str = "boot_flap"
    reconnects: int = 1
    mutants: frozenset[str] = frozenset()


class BootFlapModel(Model):
    """A client boot request arrives while every boot-capable game is
    mid-reconnect (dispatcher/service.py:985-1026): the request buffers
    for the grace window and retries each tick; only a window that
    lapses with no game drops it."""

    def __init__(self, cfg: BootConfig) -> None:
        self.cfg = cfg
        self.name = cfg.name

    def initial(self) -> BootState:
        return BootState(link=LINK_GRACE, boot="pending",
                         reconnects_left=self.cfg.reconnects)

    def actions(self, st: State) -> list[Step]:
        assert isinstance(st, BootState)
        s = st
        steps: list[Step] = []
        if s.boot == "pending":
            if s.link == LINK_CONN:
                steps.append(Step("dispatcher: boot served immediately",
                                  s._replace(boot="served")))
            elif "drop_boot_no_game" in self.cfg.mutants:
                steps.append(Step(
                    "dispatcher: no game -> boot DROPPED [mutant]",
                    s._replace(boot="dropped")))
            else:
                steps.append(Step(
                    "dispatcher: no game -> buffer boot for the grace "
                    "window (:995-1003)",
                    s._replace(boot="buffered")))
        if s.link == LINK_GRACE and s.reconnects_left:
            steps.append(Step(
                "game: reconnects within the grace window",
                s._replace(link=LINK_CONN,
                           reconnects_left=s.reconnects_left - 1)))
        if s.link == LINK_GRACE:
            steps.append(Step("dispatcher: grace window expires",
                              s._replace(link=LINK_DEAD)))
        if s.boot == "buffered" and s.link == LINK_CONN:
            steps.append(Step(
                "dispatcher: tick retry serves the buffered boot "
                "(:1012-1026)", s._replace(boot="served")))
        if s.boot == "buffered" and s.link == LINK_DEAD:
            steps.append(Step(
                "dispatcher: boot window lapsed with no game; dropped",
                s._replace(boot="dropped")))
        return steps

    def terminal_violations(self, st: State) -> tuple[str, ...]:
        assert isinstance(st, BootState)
        s = st
        if s.boot == "dropped" and s.link == LINK_CONN:
            return ("boot request dropped even though a game "
                    "reconnected — every boot must eventually be served",)
        if s.boot not in ("served", "dropped"):
            return (f"terminal state with boot still {s.boot!r}",)
        return ()


# --- the whole-space migration model -----------------------------------------
#
# One space "S" with one member "M" on game 1, one dispatcher, one
# handoff toward game 2, one joiner "J" trying to enter mid-flight.  The
# protocol is freeze-fence + fat transfer with bounce-home: the donor
# freezes membership, broadcasts SPACE_MIGRATE_PREPARE so every owning
# dispatcher parks the members' streams and acks on the SAME FIFO the
# parked traffic rode (the freeze-ack fence of game/service.py), packs
# the snapshot only after every ack (so nothing sent pre-park can be
# lost), and ships one SPACE_MIGRATE_DATA that is routed exactly like
# REAL_MIGRATE — buffer behind a grace window, bounce HOME to the donor
# on a dead target.  COMMIT is successful restore + NOTIFY_CREATE
# rerouting; ABORT is the donor deadline (or a dead-target reply)
# unfreezing in place.  I1/I2/I3 extend verbatim to the space copy and
# the member; I4 (gate generations) is untouched by this protocol.
#
# Scope honesty: one dispatcher stands in for the all-dispatcher
# broadcast (per-dispatcher behavior is symmetric and the fence is
# per-FIFO); the donor game never crashes (after DATA leaves, the donor
# holds nothing — chaos covers donor kills); space-targeted RPC parking
# is not modeled (members' sync traffic is the load-bearing case).

S_PREP_M = ("SPACE_MIGRATE_PREPARE", "members=M")
S_PREP_0 = ("SPACE_MIGRATE_PREPARE", "members=")
S_PACKACK = ("SPACE_MIGRATE_PREPARE_ACK",)
S_DATA = ("SPACE_MIGRATE_DATA",)
S_ABORT_G = ("SPACE_MIGRATE_ABORT", "from_game")
S_ABORT_D = ("SPACE_MIGRATE_ABORT", "from_dispatcher")
S_CREATE = ("NOTIFY_CREATE_SPACE",)
SM_CREATE = ("NOTIFY_CREATE_MEMBER",)
SM_JOIN = ("JOIN_SPACE",)


class SpaceMigState(NamedTuple):
    g_alive: tuple[bool, bool]
    g_space: tuple[str, str]   # none | live | frozen
    sm: str        # donor handoff: idle|preparing|sent|aborted|rolled
    mm: str        # member entity-migrate: idle|requested|cancelled|sent
    m_members: bool            # M is in S's (frozen) membership/snapshot
    m_solo: int                # 0, or the game hosting M standalone
    links: tuple[str, str]
    s_route: int
    m_route: int
    m_blocked: bool
    m_parked: Chan
    j: str   # out|pending|queued|in_frozen|in|dropped|destroyed
    gpending: tuple[Chan, Chan]
    to_g: tuple[Chan, Chan]
    from_g: tuple[Chan, Chan]
    crashes_left: int
    restarts_left: int
    syncs_left: int
    joins_left: int
    cancels_left: int
    migrates_left: int
    member_migrates_left: int
    crash_lost: bool


@dataclasses.dataclass(frozen=True)
class SpaceMigConfig:
    name: str = "space_handoff"
    crashes: int = 1           # crash budget for game 2 (the receiver)
    restarts: int = 1
    syncs: int = 1             # member-position syncs injected at D
    joins: int = 1             # joiner enter-space attempts
    cancels: int = 1           # donor deadline-abort budget
    migrates: int = 1          # whole-space handoff attempts
    member_migrates: int = 0   # member's own entity-migrate attempts
    mutants: frozenset[str] = frozenset()


class SpaceMigrateModel(Model):
    """rebalance/migrator.py space states + dispatcher parking +
    entity_manager pack/restore, reduced to the fate of S, M and J
    under every interleaving."""

    def __init__(self, cfg: SpaceMigConfig) -> None:
        bad = cfg.mutants - set(MUTANTS)
        if bad:
            raise ValueError(f"unknown mutants {sorted(bad)}")
        self.cfg = cfg
        self.name = cfg.name

    def initial(self) -> SpaceMigState:
        cfg = self.cfg
        return SpaceMigState(
            g_alive=(True, True), g_space=("live", "none"),
            sm="idle", mm="idle", m_members=True, m_solo=0,
            links=(LINK_CONN, LINK_CONN), s_route=1, m_route=1,
            m_blocked=False, m_parked=(), j="out",
            gpending=((), ()), to_g=((), ()), from_g=((), ()),
            crashes_left=cfg.crashes, restarts_left=cfg.restarts,
            syncs_left=cfg.syncs, joins_left=cfg.joins,
            cancels_left=cfg.cancels, migrates_left=cfg.migrates,
            member_migrates_left=cfg.member_migrates, crash_lost=False)

    # -- shared sub-rules ---------------------------------------------------

    def _deliver(self, s: SpaceMigState, gi: int, msg: Msg
                 ) -> SpaceMigState:
        link = s.links[gi]
        if link == LINK_CONN:
            return s._replace(to_g=_put(s.to_g, gi, msg))
        if link in (LINK_GRACE, LINK_UNREG):
            return s._replace(gpending=_put(s.gpending, gi, msg))
        return s

    def _flush_m(self, s: SpaceMigState, gi: int) -> SpaceMigState:
        out = s
        for msg in s.m_parked:
            out = self._deliver(out, gi, msg)
        return out._replace(m_parked=(), m_blocked=False)

    def _s_copies(self, s: SpaceMigState) -> int:
        chans: Iterable[Chan] = (*s.to_g, *s.from_g, *s.gpending)
        in_flight = sum(1 for c in chans for m in c if m == S_DATA)
        return sum(1 for g in s.g_space if g in ("live", "frozen")) \
            + in_flight

    def _m_copies(self, s: SpaceMigState) -> int:
        chans: Iterable[Chan] = (*s.to_g, *s.from_g, *s.gpending)
        rmig = sum(1 for c in chans for m in c if m == M_RMIG)
        inside = self._s_copies(s) if s.m_members else 0
        return inside + (1 if s.m_solo else 0) + rmig

    def _m_hosted(self, s: SpaceMigState, gi: int) -> bool:
        return (s.m_members and s.g_space[gi] in ("live", "frozen")) \
            or s.m_solo == gi + 1

    def _unfreeze(self, s: SpaceMigState) -> SpaceMigState:
        """Abort-in-place: space back to live, queued joins replay
        (Space._pending_enters replay on unfreeze)."""
        if "no_unfreeze_on_abort" in self.cfg.mutants:
            return s
        nxt = s._replace(g_space=("live", s.g_space[1]))
        if nxt.j in ("queued", "in_frozen"):
            nxt = nxt._replace(j="in")
        return nxt

    # -- actions ------------------------------------------------------------

    def actions(self, st: State) -> list[Step]:
        assert isinstance(st, SpaceMigState)
        s = st
        cfg = self.cfg
        steps: list[Step] = []

        # planner command lands: freeze S, cancel members' pending
        # entity migrates (frozen membership IS the pack list), send
        # PREPARE to the dispatcher
        if (s.migrates_left and s.sm == "idle" and s.g_alive[0]
                and s.g_space[0] == "live"):
            # PREPARE carries the freeze-time member list: a member
            # that already migrated out is not parked
            prep = S_PREP_M if s.m_members else S_PREP_0
            nxt = s._replace(
                sm="preparing", g_space=("frozen", s.g_space[1]),
                migrates_left=s.migrates_left - 1,
                from_g=_put(s.from_g, 0, prep))
            if (s.mm == "requested"
                    and "no_freeze_cancel_member" not in cfg.mutants):
                nxt = nxt._replace(mm="cancelled")
            steps.append(Step(
                "game1: freeze S (cancel member migrates) -> "
                "SPACE_MIGRATE_PREPARE", nxt))

        # member M starts its own entity migrate (only while the space
        # is live — migrator eligibility skips frozen-space members)
        if (s.member_migrates_left and s.mm == "idle" and s.m_members
                and s.g_space[0] == "live" and s.sm == "idle"
                and s.g_alive[0]):
            steps.append(Step(
                "game1: member M sends MIGRATE_REQUEST",
                s._replace(mm="requested",
                           member_migrates_left=s.member_migrates_left - 1,
                           from_g=_put(s.from_g, 0, M_MREQ))))

        # donor deadline while awaiting acks -> ABORT: unfreeze in
        # place + broadcast the abort so dispatchers unpark
        if s.cancels_left and s.sm == "preparing":
            nxt = self._unfreeze(s)._replace(
                sm="aborted", cancels_left=s.cancels_left - 1,
                from_g=_put(s.from_g, 0, S_ABORT_G))
            steps.append(Step(
                "game1: PREPARE deadline -> abort, unfreeze S in place",
                nxt))

        # a member-position sync record reaches the dispatcher
        if s.syncs_left:
            nxt = s._replace(syncs_left=s.syncs_left - 1)
            if s.m_blocked:
                nxt = nxt._replace(m_parked=nxt.m_parked + (M_SYNC,))
            elif s.m_route == 0:
                nxt = nxt._replace(m_parked=nxt.m_parked + (M_SYNC,))
            else:
                nxt = self._deliver(nxt, s.m_route - 1, M_SYNC)
            steps.append(Step("gate: SYNC(M) reaches dispatcher", nxt))

        # a joiner's enter-space request reaches the dispatcher and is
        # routed by S's routing entry
        if s.joins_left and s.j == "out":
            nxt = s._replace(joins_left=s.joins_left - 1)
            if s.s_route == 0 or s.links[s.s_route - 1] == LINK_DEAD:
                nxt = nxt._replace(j="dropped")  # client retries (legal)
            else:
                nxt = self._deliver(
                    nxt._replace(j="pending"), s.s_route - 1, SM_JOIN)
            steps.append(Step("client: J requests to join S", nxt))

        # deliver game -> dispatcher
        for gi in (0, 1):
            if s.from_g[gi]:
                msg, from_g = _pop(s.from_g, gi)
                steps.append(self._dispatcher_handle(
                    s._replace(from_g=from_g), gi, msg))

        # deliver dispatcher -> game
        for gi in (0, 1):
            if s.to_g[gi]:
                msg, to_g = _pop(s.to_g, gi)
                steps.append(self._game_handle(
                    s._replace(to_g=to_g), gi, msg))

        # crash game 2 (the receiver)
        if s.crashes_left and s.g_alive[1]:
            lost = (s.g_space[1] in ("live", "frozen")
                    or s.m_solo == 2
                    or any(m in (S_DATA, M_RMIG) for m in s.to_g[1]))
            nxt = s._replace(
                g_alive=(s.g_alive[0], False),
                g_space=(s.g_space[0], "none"),
                m_solo=0 if s.m_solo == 2 else s.m_solo,
                crashes_left=s.crashes_left - 1,
                to_g=(s.to_g[0], ()), from_g=(s.from_g[0], ()),
                links=(s.links[0],
                       LINK_GRACE if s.links[1] == LINK_CONN
                       else s.links[1]),
                crash_lost=s.crash_lost or lost)
            if s.j == "pending" and SM_JOIN in s.to_g[1]:
                nxt = nxt._replace(j="dropped")
            steps.append(Step("game2: CRASH", nxt))

        # cold restart of game 2
        if s.restarts_left and not s.g_alive[1]:
            steps.append(Step(
                "game2: cold restart -> SET_GAME_ID(cold)",
                s._replace(g_alive=(s.g_alive[0], True),
                           restarts_left=s.restarts_left - 1,
                           from_g=_put(s.from_g, 1, M_HSHAKE_COLD))))

        # reconnect-grace expiry on game 2
        if s.links[1] == LINK_GRACE:
            steps.append(self._expire_game2(s))

        # park-deadline sweep: parked traffic for a crash-lost member
        # is dropped (the real block() window has a wall-clock deadline;
        # a sync for an entity with no live copy is a legal drop)
        if (s.m_blocked and s.crash_lost and self._m_copies(s) == 0
                and not s.from_g[0] and not s.from_g[1]):
            steps.append(Step(
                "dispatcher: park deadline sweep (member crash-lost)",
                s._replace(m_blocked=False, m_parked=())))

        # unrouted sweep for M's parked packets (same rule as the
        # entity model)
        if (s.m_route == 0 and s.m_parked and not s.m_blocked
                and not any(SM_CREATE in c for c in s.from_g)):
            steps.append(Step(
                "dispatcher: unrouted sweep drops M's parked packets",
                s._replace(m_parked=())))

        return steps

    # -- dispatcher ---------------------------------------------------------

    def _dispatcher_handle(self, s: SpaceMigState, gi: int, msg: Msg
                           ) -> Step:
        g = f"game{gi + 1}"
        cfg = self.cfg
        if msg in (S_PREP_M, S_PREP_0):
            # park every LISTED member stream this dispatcher owns,
            # then ack on the donor's FIFO — the ack fences all
            # pre-park traffic
            if s.links[1] == LINK_DEAD:
                nxt = self._deliver(s, 0, S_ABORT_D)
                return Step("dispatcher: PREPARE -> target dead, reply "
                            "ABORT", nxt)
            nxt = s
            if msg == S_PREP_M and "no_space_park" not in cfg.mutants:
                nxt = nxt._replace(m_blocked=True)
            nxt = self._deliver(nxt, 0, S_PACKACK)
            return Step("dispatcher: PREPARE -> park listed members, "
                        "ack", nxt)
        if msg == S_ABORT_G:
            # donor aborted: unpark members, flush to their route
            nxt = s
            if s.m_route:
                nxt = self._flush_m(s, s.m_route - 1)
            nxt = nxt._replace(m_blocked=False)
            return Step("dispatcher: space ABORT -> unpark M", nxt)
        if msg == S_DATA:
            return self._route_space_data(s)
        if msg == S_CREATE:
            return Step(f"dispatcher: {g} NOTIFY_CREATE(S) -> route S",
                        s._replace(s_route=gi + 1))
        if msg == SM_CREATE:
            nxt = self._flush_m(s._replace(m_route=gi + 1), gi)
            return Step(f"dispatcher: {g} NOTIFY_CREATE(M) -> route M, "
                        f"flush parked", nxt)
        if msg == SM_JOIN:
            # a join bounced off a copy-less game: re-route by S's
            # current entry (enter_space re-resolution)
            if s.s_route == 0 or s.links[s.s_route - 1] == LINK_DEAD:
                return Step("dispatcher: J's join has no routable S -> "
                            "dropped (client retries)",
                            s._replace(j="dropped"))
            nxt = self._deliver(s, s.s_route - 1, SM_JOIN)
            return Step("dispatcher: re-route J's join", nxt)
        if msg == M_MREQ:
            nxt = self._deliver(s._replace(m_blocked=True), 0, M_MACK)
            return Step("dispatcher: M MIGRATE_REQUEST -> block M, ack",
                        nxt)
        if msg == M_RMIG:
            if s.links[1] in (LINK_CONN, LINK_GRACE, LINK_UNREG):
                nxt = self._deliver(s._replace(m_route=2), 1, M_RMIG)
                return Step("dispatcher: REAL_MIGRATE(M) -> game2", nxt)
            nxt = self._deliver(s._replace(m_route=1), 0, M_RMIG)
            return Step("dispatcher: REAL_MIGRATE(M) -> target dead, "
                        "bounce HOME", nxt)
        if msg == M_HSHAKE_COLD:
            nxt = s
            if nxt.s_route == 2:
                nxt = nxt._replace(s_route=0)
            if nxt.m_route == 2:
                nxt = nxt._replace(m_route=0)
            links = (nxt.links[0], LINK_CONN)
            flushed = nxt.gpending[1]
            nxt = nxt._replace(
                links=links, gpending=(nxt.gpending[0], ()),
                to_g=_put(nxt.to_g, 1, *flushed))
            return Step(f"dispatcher: {g} cold handshake -> purge "
                        f"routes, flush {len(flushed)} buffered", nxt)
        raise AssertionError(f"unmodeled dispatcher message {msg}")

    def _route_space_data(self, s: SpaceMigState) -> Step:
        """SPACE_MIGRATE_DATA routes exactly like REAL_MIGRATE: forward,
        buffer behind grace, or bounce the whole space HOME."""
        tlink = s.links[1]
        if tlink in (LINK_CONN, LINK_GRACE, LINK_UNREG):
            nxt = self._deliver(s._replace(s_route=2), 1, S_DATA)
            return Step("dispatcher: SPACE_DATA(S) -> route to game2",
                        nxt)
        if "no_space_bounce" in self.cfg.mutants:
            nxt = s._replace(s_route=0)
            return Step("dispatcher: SPACE_DATA(S) -> target dead, "
                        "payload DROPPED [mutant]", nxt,
                        ("space S's last copy dropped at the dispatcher "
                         "(dead target, no bounce)",))
        nxt = self._deliver(s._replace(s_route=1), 0, S_DATA)
        return Step("dispatcher: SPACE_DATA(S) -> target dead, bounce "
                    "HOME to game1", nxt)

    # -- games --------------------------------------------------------------

    def _game_handle(self, s: SpaceMigState, gi: int, msg: Msg) -> Step:
        g = f"game{gi + 1}"
        if msg == S_PACKACK:
            if gi == 0 and s.sm == "preparing":
                return self._pack(s)
            return Step(f"{g}: stale PREPARE_ACK ignored", s)
        if msg == S_ABORT_D:
            if gi == 0 and s.sm == "preparing":
                nxt = self._unfreeze(s)._replace(
                    sm="aborted", from_g=_put(s.from_g, 0, S_ABORT_G))
                return Step(f"{g}: dispatcher ABORT -> unfreeze S in "
                            f"place", nxt)
            return Step(f"{g}: stale space ABORT ignored", s)
        if msg == S_DATA:
            spaces = list(s.g_space)
            spaces[gi] = "live"
            sm = "rolled" if gi == 0 else s.sm
            creates = (S_CREATE,) + ((SM_CREATE,) if s.m_members else ())
            nxt = s._replace(
                g_space=(spaces[0], spaces[1]), sm=sm,
                from_g=_put(s.from_g, gi, *creates))
            kind = "bounced home (rollback + cooldown)" if gi == 0 \
                else "arrives"
            return Step(f"{g}: SPACE_DATA(S) {kind} -> restore live, "
                        f"NOTIFY_CREATEs", nxt)
        if msg == M_MACK:
            if gi == 0 and s.mm == "requested":
                # membership was fixed at freeze time: a frozen space
                # still counts M in its snapshot (only reachable under
                # the no_freeze_cancel_member mutant)
                members = s.g_space[0] != "live"
                nxt = s._replace(
                    mm="sent", m_members=members,
                    from_g=_put(s.from_g, 0, M_RMIG))
                return Step(f"{g}: M MIGRATE_REQUEST_ACK -> send "
                            f"REAL_MIGRATE(M), drop local copy", nxt)
            return Step(f"{g}: stale MIGRATE_REQUEST_ACK ignored", s)
        if msg == M_RMIG:
            nxt = s._replace(m_solo=gi + 1,
                             from_g=_put(s.from_g, gi, SM_CREATE))
            return Step(f"{g}: REAL_MIGRATE(M) arrives -> restore, "
                        f"NOTIFY_CREATE", nxt)
        if msg == M_SYNC:
            viols: tuple[str, ...] = ()
            if not self._m_hosted(s, gi) and self._m_copies(s) >= 1:
                viols = (f"sync record for M delivered to {g} while M's "
                         f"live copy is elsewhere (stale-game delivery)",)
            return Step(f"{g}: SYNC(M) delivered", s, viols)
        if msg == SM_JOIN:
            state = s.g_space[gi]
            if state == "live":
                return Step(f"{g}: J enters live S", s._replace(j="in"))
            if state == "frozen":
                if "no_frozen_join_guard" in self.cfg.mutants:
                    return Step(f"{g}: J enters FROZEN S [mutant]",
                                s._replace(j="in_frozen"))
                return Step(f"{g}: S frozen -> queue J's enter",
                            s._replace(j="queued"))
            # no copy here (stale delivery window): bounce to re-route
            nxt = s._replace(from_g=_put(s.from_g, gi, SM_JOIN))
            return Step(f"{g}: no S here -> bounce J's join", nxt)
        raise AssertionError(f"unmodeled game message {msg}")

    def _pack(self, s: SpaceMigState) -> Step:
        """All dispatcher acks in: pack the frozen membership, destroy
        the local copies, ship SPACE_MIGRATE_DATA.  Queued joiners are
        re-dispatched AFTER the data on the same FIFO."""
        viols: list[str] = []
        nxt = s._replace(
            g_space=("none", s.g_space[1]), sm="sent",
            from_g=_put(s.from_g, 0, S_DATA))
        if s.j == "queued":
            nxt = nxt._replace(j="pending",
                               from_g=_put(nxt.from_g, 0, SM_JOIN))
        elif s.j == "in_frozen":
            viols.append(
                "joiner J entered the FROZEN space and was destroyed by "
                "the pack (absent from the freeze-time snapshot)")
            nxt = nxt._replace(j="destroyed")
        return Step("game1: all PREPARE acks in -> pack S(+M), destroy "
                    "local, send SPACE_DATA", nxt, tuple(viols))

    def _expire_game2(self, s: SpaceMigState) -> Step:
        """Grace lapse on the receiver: bounce buffered space payloads
        (and member migrates) home, drop the rest, purge routes."""
        nxt = s
        viols: list[str] = []
        for msg in s.gpending[1]:
            if msg == S_DATA:
                if "no_space_bounce" in self.cfg.mutants:
                    viols.append("space S's last copy dropped at grace "
                                 "expiry (no bounce)")
                    nxt = nxt._replace(s_route=0)
                else:
                    nxt = self._deliver(
                        nxt._replace(s_route=1), 0, S_DATA)
            elif msg == M_RMIG:
                nxt = self._deliver(nxt._replace(m_route=1), 0, M_RMIG)
            elif msg == SM_JOIN:
                nxt = nxt._replace(j="dropped")
            # parked syncs etc. drop with the window
        nxt = nxt._replace(gpending=(nxt.gpending[0], ()),
                           links=(nxt.links[0], LINK_DEAD))
        if nxt.s_route == 2:
            nxt = nxt._replace(s_route=0)
        if nxt.m_route == 2:
            nxt = nxt._replace(m_route=0)
        return Step("dispatcher: game2 grace window expires -> declared "
                    "dead", nxt, tuple(viols))

    # -- invariants ---------------------------------------------------------

    def state_invariants(self, st: State) -> tuple[str, ...]:
        assert isinstance(st, SpaceMigState)
        s = st
        out: list[str] = []
        sc = self._s_copies(s)
        if sc > 1:
            out.append(f"space S duplicated: {sc} live copies")
        if sc == 0 and not s.crash_lost:
            out.append("space S vanished with no crash to blame")
        mc = self._m_copies(s)
        if mc > 1:
            out.append(f"member M duplicated: {mc} live copies")
        if mc == 0 and not s.crash_lost:
            out.append("member M vanished with no crash to blame")
        return tuple(out)

    def terminal_violations(self, st: State) -> tuple[str, ...]:
        assert isinstance(st, SpaceMigState)
        s = st
        out: list[str] = []
        if "frozen" in s.g_space:
            out.append("terminal state: space S FROZEN forever — "
                       "abort/commit never unfroze it")
        hosted_live = any(s.g_space[i] == "live" and s.g_alive[i]
                          for i in (0, 1))
        if not hosted_live and not s.crash_lost:
            out.append("terminal state: S is not live on any live game")
        if s.sm == "preparing":
            out.append("terminal state: handoff wedged in PREPARE")
        if s.s_route and s.g_space[s.s_route - 1] == "none":
            out.append(f"terminal state: stale routing-table entry — S "
                       f"routed to game{s.s_route} which does not host "
                       f"it")
        if (s.m_route and not self._m_hosted(s, s.m_route - 1)
                and not s.crash_lost):
            out.append(f"terminal state: stale routing-table entry — M "
                       f"routed to game{s.m_route} which does not host "
                       f"it")
        if any(S_DATA in gp or M_RMIG in gp for gp in s.gpending):
            out.append("terminal state: migrate payload stuck in a "
                       "dispatcher buffer forever")
        if s.m_blocked and not s.crash_lost:
            out.append("terminal state: M's stream parked forever")
        if s.m_parked and not s.crash_lost:
            out.append("terminal state: M's parked packets never "
                       "flushed")
        if s.j in ("pending", "queued", "in_frozen"):
            out.append(f"terminal state: joiner J stuck ({s.j})")
        return tuple(out)


# --- entry points ------------------------------------------------------------


def tier1_configs() -> list[Model]:
    """The bounded configurations tier-1 explores exhaustively."""
    return [
        MigrateCrashModel(MigConfig()),
        MigrateCrashModel(MigConfig(name="migrate_unknown_target",
                                    target_unregistered=True)),
        # the crashed target never comes back: grace expiry MUST bounce
        # the payload home (this is the config that exposes a widened
        # grace window — see the infinite_grace mutant)
        MigrateCrashModel(MigConfig(name="migrate_no_return",
                                    restarts=0)),
        GateGenerationModel(GateGenConfig()),
        BootFlapModel(BootConfig()),
        # whole-space handoff: crash/restart/expiry x abort-deadline x
        # member sync parking x joiner queueing
        SpaceMigrateModel(SpaceMigConfig()),
        # the member-migrates-while-the-space-moves race (freeze must
        # cancel the member's in-flight entity migrate)
        SpaceMigrateModel(SpaceMigConfig(
            name="space_member_race", crashes=0, restarts=0, joins=0,
            member_migrates=1)),
    ]


def deep_configs() -> list[Model]:
    """Wider bounds for the slow suite: more crash/restart/sync budget
    around the same machine."""
    return [
        MigrateCrashModel(MigConfig(
            name="migrate_crash_deep", crashes=2, restarts=2, syncs=2,
            cancels=1)),
        MigrateCrashModel(MigConfig(
            name="migrate_unknown_deep", target_unregistered=True,
            crashes=1, restarts=2, syncs=2)),
        SpaceMigrateModel(SpaceMigConfig(
            name="space_handoff_deep", syncs=2, member_migrates=1)),
    ]


def check_all(models: Iterable[Model],
              max_states: int = 1_000_000) -> list[CheckResult]:
    return [explore(m, max_states=max_states) for m in models]


def main(argv: Optional[list[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="exhaustive cluster-protocol model checker")
    ap.add_argument("--deep", action="store_true",
                    help="also run the slow-suite configurations")
    args = ap.parse_args(argv)
    models = tier1_configs() + (deep_configs() if args.deep else [])
    rc = 0
    for result in check_all(models):
        print(result.render())
        if not result.ok:
            rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())

"""Explicit-state model checker for the cluster protocol.

A compact Python model of the dispatcher<->game<->gate state machines —
client-binding generations, migrate target states (connected / blocked /
UNKNOWN / declared-DEAD), reconnect-grace windows, pending-sync parking,
buffered boots — explored EXHAUSTIVELY over bounded interleavings of
message delivery, process crash / cold restart, and grace expiry.  The
transition rules mirror the shipped code path by path (each cites its
``file:line``), so the model is the SPEC: the next protocol PR extends
the model first and lands against these invariants instead of against
production.

Invariants (the PR-9 zero-loss contract, asserted in every reached state
and at every quiescent terminal state):

- **I1 no lost / duplicate entity** — an entity has exactly one live
  copy across games, in-flight ``REAL_MIGRATE`` payloads, and dispatcher
  grace buffers; a copy count of zero is legal only after the process
  HOSTING the copy (or holding it on a dying socket) crashed.
- **I2 no stale sync delivery** — a position-sync record is never
  delivered to a game that does not host its entity (parking + FIFO
  flush-behind-``REAL_MIGRATE`` is what guarantees it).
- **I3 no stuck terminal** — when no action remains, the entity lives on
  a live game (unless crash-lost), nothing sits in a buffer forever, and
  every boot request was served unless its only game stayed dead.
- **I4 generation-scoped detach** — a gate-restart detach broadcast
  never removes a binding of the valid (new) generation, under any
  cross-dispatcher delivery order.

Scope honesty: the exploration is BOUNDED (budgets below) and the model
abstracts time into nondeterministic grace-expiry events — it proves the
protocol LOGIC under every interleaving within the bounds, not liveness
under real clocks, and not payload encoding (gwlint R7 owns layout).

``python -m goworld_tpu.analysis.modelcheck`` runs the tier-1 configs
and reports deterministic state counts (tools/lint.sh wires it in).

Seeded mutants (``mutants=`` on a config) flip one protocol rule each;
tests/test_modelcheck.py proves every one is caught — the checker has
teeth, not just green lights.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Iterable, NamedTuple, Optional

Msg = tuple[str, ...]
Chan = tuple[Msg, ...]

#: Known mutant switches (test_modelcheck pins each one caught).
MUTANTS = (
    "no_bounce",          # dead-target REAL_MIGRATE dropped, not bounced home
    "no_purge_cold_boot",  # cold handshake keeps the dead incarnation's routes
    "infinite_grace",     # reconnect-grace windows never expire
    "no_sync_parking",    # syncs for a blocked (migrating) entity route anyway
    "skip_gen_check",     # gate-restart detach ignores the valid generation
    "drop_boot_no_game",  # boot with no connected game dropped, not buffered
)


# --- framework ---------------------------------------------------------------


class Step(NamedTuple):
    label: str
    state: "State"
    violations: tuple[str, ...] = ()


State = tuple  # models return hashable NamedTuples (subtypes of tuple)


class Model:
    """Interface an explorable protocol model implements."""

    name = "model"

    def initial(self) -> State:
        raise NotImplementedError

    def actions(self, s: State) -> list[Step]:
        raise NotImplementedError

    def state_invariants(self, s: State) -> tuple[str, ...]:
        return ()

    def terminal_violations(self, s: State) -> tuple[str, ...]:
        return ()


@dataclasses.dataclass
class Counterexample:
    message: str
    trace: tuple[str, ...]

    def render(self) -> str:
        lines = [f"violation: {self.message}", "  trace:"]
        lines += [f"    {i + 1:2d}. {step}"
                  for i, step in enumerate(self.trace)]
        return "\n".join(lines)


@dataclasses.dataclass
class CheckResult:
    model: str
    states: int
    transitions: int
    terminals: int
    violations: list[Counterexample]

    @property
    def ok(self) -> bool:
        return not self.violations

    def render(self) -> str:
        head = (f"{self.model}: {self.states} states, "
                f"{self.transitions} transitions, {self.terminals} "
                f"terminal state(s), {len(self.violations)} violation(s)")
        return "\n".join([head] + [v.render() for v in self.violations])


def explore(model: Model, max_states: int = 1_000_000,
            max_counterexamples: int = 8) -> CheckResult:
    """Exhaustive BFS over the model's reachable states.  Deterministic:
    identical models explore identical state counts in identical order
    (actions are returned in rule order; the frontier is FIFO)."""
    init = model.initial()
    parents: dict[State, Optional[tuple[State, str]]] = {init: None}
    frontier: deque[State] = deque([init])
    violations: list[Counterexample] = []
    transitions = 0
    terminals = 0

    def trace_to(s: State, last: Optional[str] = None) -> tuple[str, ...]:
        labels: list[str] = [] if last is None else [last]
        cur: Optional[tuple[State, str]] = parents[s]
        while cur is not None:
            labels.append(cur[1])
            cur = parents[cur[0]]
        return tuple(reversed(labels))

    def report(msg: str, s: State, last: Optional[str] = None) -> None:
        if len(violations) < max_counterexamples:
            violations.append(Counterexample(msg, trace_to(s, last)))

    for msg in model.state_invariants(init):
        report(msg, init)
    while frontier:
        if len(parents) > max_states:
            raise RuntimeError(
                f"{model.name}: state space exceeded {max_states} — "
                f"tighten the config bounds")
        s = frontier.popleft()
        steps = model.actions(s)
        if not steps:
            terminals += 1
            for msg in model.terminal_violations(s):
                report(msg, s)
            continue
        for label, nxt, viols in steps:
            transitions += 1
            for msg in viols:
                report(msg, s, label)
            if nxt not in parents:
                parents[nxt] = (s, label)
                frontier.append(nxt)
                for msg in model.state_invariants(nxt):
                    report(msg, nxt)
    return CheckResult(model.name, len(parents), transitions, terminals,
                       violations)


# --- the migrate + crash model ----------------------------------------------
#
# One entity "E" on game 1, one dispatcher, one migration toward game 2.
# Game indices are 0-based internally, 1-based in labels.  Each rule
# cites the code it mirrors.

LINK_CONN = "conn"
LINK_GRACE = "grace"
LINK_UNREG = "unreg"
LINK_DEAD = "dead"

M_MREQ = ("MIGRATE_REQUEST",)
M_MACK = ("MIGRATE_REQUEST_ACK",)
M_RMIG = ("REAL_MIGRATE",)
M_SYNC = ("SYNC_POSITION",)
M_CANCEL = ("CANCEL_MIGRATE",)
M_CREATE = ("NOTIFY_CREATE_ENTITY",)
M_HSHAKE_COLD = ("SET_GAME_ID", "cold")


class MigState(NamedTuple):
    g_alive: tuple[bool, bool]
    g_has_e: tuple[bool, bool]
    g1_migrate: str       # idle | requested | sent | cancelled | closed
    links: tuple[str, str]
    route: int            # 0 unrouted, 1, 2
    blocked: bool         # dispatcher migrate window for E
    parked: Chan          # per-entity pending queue (parked syncs)
    gpending: tuple[Chan, Chan]   # per-game grace buffers
    to_g: tuple[Chan, Chan]       # dispatcher -> game FIFOs
    from_g: tuple[Chan, Chan]     # game -> dispatcher FIFOs
    crashes_left: int
    restarts_left: int
    syncs_left: int
    cancels_left: int
    migrates_left: int
    crash_lost: bool


def _put(chans: tuple[Chan, Chan], i: int, *msgs: Msg
         ) -> tuple[Chan, Chan]:
    out = list(chans)
    out[i] = out[i] + tuple(msgs)
    return (out[0], out[1])


def _pop(chans: tuple[Chan, Chan], i: int) -> tuple[Msg, tuple[Chan, Chan]]:
    out = list(chans)
    head, out[i] = out[i][0], out[i][1:]
    return head, (out[0], out[1])


@dataclasses.dataclass(frozen=True)
class MigConfig:
    name: str = "migrate_crash"
    crashes: int = 1          # crash budget for game 2 (the target)
    restarts: int = 1         # cold-restart budget for game 2
    syncs: int = 1            # position-sync records injected at D
    cancels: int = 1          # migrator deadline-cancel budget
    migrates: int = 1
    target_unregistered: bool = False  # UNKNOWN-target start (replayed
    #                                    RMIG racing a re-handshake)
    mutants: frozenset[str] = frozenset()


class MigrateCrashModel(Model):
    """dispatcher/service.py + rebalance/migrator.py + entity manager
    notify flow, reduced to E's fate under every interleaving."""

    def __init__(self, cfg: MigConfig) -> None:
        bad = cfg.mutants - set(MUTANTS)
        if bad:
            raise ValueError(f"unknown mutants {sorted(bad)}")
        self.cfg = cfg
        self.name = cfg.name

    def initial(self) -> MigState:
        cfg = self.cfg
        return MigState(
            g_alive=(True, True),
            g_has_e=(True, False),
            g1_migrate="idle",
            links=(LINK_CONN,
                   LINK_UNREG if cfg.target_unregistered else LINK_CONN),
            route=1,
            blocked=False,
            parked=(),
            gpending=((), ()),
            to_g=((), ()),
            from_g=((), ()),
            crashes_left=cfg.crashes,
            restarts_left=cfg.restarts,
            syncs_left=cfg.syncs,
            cancels_left=cfg.cancels,
            migrates_left=cfg.migrates,
            crash_lost=False,
        )

    # -- shared sub-rules ---------------------------------------------------

    def _deliver_to_game(self, s: MigState, gi: int, msg: Msg
                         ) -> MigState:
        """_GameInfo.dispatch (dispatcher/service.py:116-122): connected
        sends, a grace/unreg window buffers, a dead game drops."""
        link = s.links[gi]
        if link == LINK_CONN:
            return s._replace(to_g=_put(s.to_g, gi, msg))
        if link in (LINK_GRACE, LINK_UNREG):
            return s._replace(gpending=_put(s.gpending, gi, msg))
        return s  # dead: drop (syncs/acks only ever reach here)

    def _flush_parked(self, s: MigState, gi: int) -> MigState:
        """_flush_entity_pending (dispatcher/service.py:774-779): parked
        packets follow E to wherever it routed, AFTER the REAL_MIGRATE on
        the same FIFO."""
        out = s
        for msg in s.parked:
            out = self._deliver_to_game(out, gi, msg)
        return out._replace(parked=(), blocked=False)

    # -- actions ------------------------------------------------------------

    def actions(self, st: State) -> list[Step]:
        assert isinstance(st, MigState)
        s = st
        cfg = self.cfg
        steps: list[Step] = []

        # migrator issues the move (rebalance/migrator.py:81-99 ->
        # entity.enter_space -> MIGRATE_REQUEST, entity.py:750-765)
        if (s.migrates_left and s.g1_migrate == "idle" and s.g_alive[0]
                and s.g_has_e[0]):
            steps.append(Step(
                "game1: send MIGRATE_REQUEST(E)",
                s._replace(g1_migrate="requested",
                           migrates_left=s.migrates_left - 1,
                           from_g=_put(s.from_g, 0, M_MREQ))))

        # migrator deadline fires (rebalance/migrator.py:143-150 ->
        # cancel_enter_space -> CANCEL_MIGRATE; the entity stays)
        if s.cancels_left and s.g1_migrate == "requested":
            steps.append(Step(
                "game1: migrate deadline -> CANCEL_MIGRATE(E)",
                s._replace(g1_migrate="cancelled",
                           cancels_left=s.cancels_left - 1,
                           from_g=_put(s.from_g, 0, M_CANCEL))))

        # a gate-side sync record reaches the dispatcher
        # (dispatcher/service.py:1222-1290)
        if s.syncs_left:
            nxt = s._replace(syncs_left=s.syncs_left - 1)
            if s.blocked and "no_sync_parking" not in cfg.mutants:
                # park with the entity's pending queue (:1246-1254)
                nxt = nxt._replace(parked=nxt.parked + (M_SYNC,))
            elif s.route == 0:
                # unrouted grace buffer (:757-767)
                nxt = nxt._replace(parked=nxt.parked + (M_SYNC,))
            else:
                nxt = self._deliver_to_game(nxt, s.route - 1, M_SYNC)
            steps.append(Step("gate: SYNC(E) reaches dispatcher", nxt))

        # deliver game -> dispatcher
        for gi in (0, 1):
            if not s.from_g[gi]:
                continue
            msg, from_g = _pop(s.from_g, gi)
            base = s._replace(from_g=from_g)
            steps.append(self._dispatcher_handle(base, gi, msg))

        # deliver dispatcher -> game
        for gi in (0, 1):
            if not s.to_g[gi]:
                continue
            msg, to_g = _pop(s.to_g, gi)
            base = s._replace(to_g=to_g)
            steps.append(self._game_handle(base, gi, msg))

        # crash game 2 (the migrate target)
        if s.crashes_left and s.g_alive[1]:
            lost = s.g_has_e[1] or any(
                m == M_RMIG for m in s.to_g[1])  # on a dying socket
            nxt = s._replace(
                g_alive=(s.g_alive[0], False),
                g_has_e=(s.g_has_e[0], False),
                crashes_left=s.crashes_left - 1,
                to_g=(s.to_g[0], ()),
                from_g=(s.from_g[0], ()),
                links=(s.links[0],
                       LINK_GRACE if s.links[1] == LINK_CONN
                       else s.links[1]),
                crash_lost=s.crash_lost or lost)
            steps.append(Step("game2: CRASH", nxt))

        # cold restart of game 2 (fresh process, empty entity set)
        if s.restarts_left and not s.g_alive[1]:
            steps.append(Step(
                "game2: cold restart -> SET_GAME_ID(cold)",
                s._replace(g_alive=(s.g_alive[0], True),
                           restarts_left=s.restarts_left - 1,
                           from_g=_put(s.from_g, 1, M_HSHAKE_COLD))))

        # an unregistered-but-alive target finally handshakes
        # (the replayed-RMIG-races-rehandshake scenario, PR 9)
        if (s.g_alive[1] and s.links[1] == LINK_UNREG
                and M_HSHAKE_COLD not in s.from_g[1]):
            steps.append(Step(
                "game2: handshake SET_GAME_ID(cold)",
                s._replace(from_g=_put(s.from_g, 1, M_HSHAKE_COLD))))

        # reconnect-grace expiry on game 2 — the sweep fires on wall
        # clock whether or not the process is back up, including the
        # alive-but-slow-to-handshake UNKNOWN-target window
        # (_sweep_dead_frozen_games:649-676 + _handle_game_down:1410-1424)
        if s.links[1] == LINK_GRACE and \
                "infinite_grace" not in cfg.mutants:
            steps.append(self._expire_game2(s))

        # unrouted-entity sweep drops parked packets for an entity no
        # game claimed (_sweep_unrouted_entities:698-715).  The window is
        # long (seconds) against an in-flight NOTIFY_CREATE (one RTT), so
        # the time-free model does not race the sweep against a CREATE
        # already on the wire.
        if (s.route == 0 and s.parked and not s.blocked
                and not any(M_CREATE in c for c in s.from_g)):
            steps.append(Step(
                "dispatcher: unrouted sweep drops parked packets",
                s._replace(parked=())))

        return steps

    def _dispatcher_handle(self, s: MigState, gi: int, msg: Msg) -> Step:
        g = f"game{gi + 1}"
        cfg = self.cfg
        viols: tuple[str, ...] = ()
        if msg == M_MREQ:
            # block E's stream, ack through the buffered path
            # (_handle_migrate_request:1122-1134)
            nxt = self._deliver_to_game(
                s._replace(blocked=True), 0, M_MACK)
            return Step(f"dispatcher: {g} MIGRATE_REQUEST -> block E, "
                        f"ack", nxt)
        if msg == M_CANCEL:
            # unblock + flush parked to E's current route
            # (_handle_cancel_migrate:1212-1218)
            nxt = s
            if s.route:
                nxt = self._flush_parked(s, s.route - 1)
            nxt = nxt._replace(blocked=False)
            return Step(f"dispatcher: {g} CANCEL_MIGRATE -> unblock E",
                        nxt)
        if msg == M_CREATE:
            # route E here, flush parked (_handle_notify_create_entity)
            nxt = self._flush_parked(s._replace(route=gi + 1), gi)
            return Step(f"dispatcher: {g} NOTIFY_CREATE -> route E", nxt)
        if msg == M_RMIG:
            return self._route_real_migrate(s)
        if msg == M_HSHAKE_COLD:
            # cold boot: purge the dead incarnation's routes, then flush
            # the grace buffer to the fresh process
            # (_handle_set_game_id:857-874 purge, 910 unblock_and_flush)
            nxt = s
            if nxt.route == gi + 1 and \
                    "no_purge_cold_boot" not in cfg.mutants:
                nxt = nxt._replace(route=0)
            links = list(nxt.links)
            links[gi] = LINK_CONN
            gp = list(nxt.gpending)
            flushed = gp[gi]
            gp[gi] = ()
            nxt = nxt._replace(
                links=(links[0], links[1]),
                gpending=(gp[0], gp[1]),
                to_g=_put(nxt.to_g, gi, *flushed))
            return Step(f"dispatcher: {g} cold handshake -> purge stale "
                        f"routes, flush {len(flushed)} buffered", nxt,
                        viols)
        raise AssertionError(f"unmodeled dispatcher message {msg}")

    def _route_real_migrate(self, s: MigState) -> Step:
        """_handle_real_migrate (dispatcher/service.py:1146-1192): route,
        buffer behind a grace window, or bounce the payload HOME — never
        drop the entity's last copy."""
        cfg = self.cfg
        tlink = s.links[1]
        if tlink == LINK_UNREG:
            # unknown target: grant the standard reconnect-grace window
            # and buffer (:1169-1176)
            nxt = s._replace(
                links=(s.links[0], LINK_GRACE), route=2,
                gpending=_put(s.gpending, 1, M_RMIG))
            nxt = self._flush_parked(nxt, 1)
            return Step("dispatcher: REAL_MIGRATE(E) -> unknown game2, "
                        "buffer behind grace window", nxt)
        if tlink in (LINK_CONN, LINK_GRACE):
            nxt = self._deliver_to_game(s._replace(route=2), 1, M_RMIG)
            nxt = self._flush_parked(nxt, 1)
            return Step("dispatcher: REAL_MIGRATE(E) -> route to game2",
                        nxt)
        # declared dead: bounce home (:1177-1192)
        if "no_bounce" in cfg.mutants:
            nxt = s._replace(route=0, blocked=False, parked=())
            return Step("dispatcher: REAL_MIGRATE(E) -> target dead, "
                        "payload DROPPED [mutant]", nxt,
                        ("entity E's last copy dropped at the "
                         "dispatcher (dead target, no bounce)",))
        if s.links[0] in (LINK_CONN, LINK_GRACE):
            nxt = self._deliver_to_game(s._replace(route=1), 0, M_RMIG)
            nxt = self._flush_parked(nxt, 0)
            return Step("dispatcher: REAL_MIGRATE(E) -> target dead, "
                        "bounce HOME to game1", nxt)
        # both ends gone: only reachable with a game-1 crash in budget
        nxt = s._replace(route=0, blocked=False, parked=(),
                         crash_lost=True)
        return Step("dispatcher: REAL_MIGRATE(E) -> both ends crashed; "
                    "state dropped", nxt)

    def _expire_game2(self, s: MigState) -> Step:
        """Grace lapse: bounce buffered REAL_MIGRATEs home, drop the
        rest, declare the game down (purging its routes)."""
        nxt = s
        viols: list[str] = []
        for msg in s.gpending[1]:
            if msg != M_RMIG:
                continue  # parked syncs etc. drop with the window
            if "no_bounce" in self.cfg.mutants:
                viols.append("entity E's last copy dropped at grace "
                             "expiry (no bounce)")
                nxt = nxt._replace(route=0, blocked=False, parked=())
            elif nxt.links[0] in (LINK_CONN, LINK_GRACE):
                nxt = self._deliver_to_game(
                    nxt._replace(route=1), 0, M_RMIG)
                nxt = self._flush_parked(nxt, 0)
            else:
                nxt = nxt._replace(route=0, crash_lost=True)
        nxt = nxt._replace(gpending=(nxt.gpending[0], ()),
                           links=(nxt.links[0], LINK_DEAD))
        if nxt.route == 2:  # _handle_game_down purges dead routes
            nxt = nxt._replace(route=0)
        return Step("dispatcher: game2 grace window expires -> declared "
                    "dead", nxt, tuple(viols))

    def _game_handle(self, s: MigState, gi: int, msg: Msg) -> Step:
        g = f"game{gi + 1}"
        if msg == M_MACK:
            # entity.py:803-847: pack state, send REAL_MIGRATE, destroy
            # the local copy.  A cancelled request ignores the stale ack.
            if gi == 0 and s.g1_migrate == "requested":
                nxt = s._replace(
                    g_has_e=(False, s.g_has_e[1]), g1_migrate="sent",
                    from_g=_put(s.from_g, 0, M_RMIG))
                return Step(f"{g}: MIGRATE_REQUEST_ACK -> send "
                            f"REAL_MIGRATE(E), drop local copy", nxt)
            return Step(f"{g}: stale MIGRATE_REQUEST_ACK ignored", s)
        if msg == M_RMIG:
            # game/service.py:712-725 restore_entity + the entity
            # manager's NOTIFY_CREATE_ENTITY (entity_manager.py:503)
            has = list(s.g_has_e)
            has[gi] = True
            mig = "closed" if gi == 0 else s.g1_migrate
            nxt = s._replace(g_has_e=(has[0], has[1]), g1_migrate=mig,
                             from_g=_put(s.from_g, gi, M_CREATE))
            kind = "bounced home" if gi == 0 else "arrives"
            return Step(f"{g}: REAL_MIGRATE(E) {kind} -> restore, "
                        f"NOTIFY_CREATE", nxt)
        if msg == M_SYNC:
            # The PR-9 parking clause: a record must never reach a game
            # OTHER than the one holding E's live copy.  A record for an
            # entity with no live copy anywhere (crash-lost) is dropped
            # by ``get_entity -> None`` (game/service.py:667-670) — a
            # legal drop, not a mis-route.
            viols2: tuple[str, ...] = ()
            if not s.g_has_e[gi] and self._copies(s) >= 1:
                viols2 = (f"sync record for E delivered to {g} while E's "
                          f"live copy is elsewhere (stale-game delivery)",)
            return Step(f"{g}: SYNC(E) delivered", s, viols2)
        raise AssertionError(f"unmodeled game message {msg}")

    # -- invariants ---------------------------------------------------------

    def _copies(self, s: MigState) -> int:
        chans: Iterable[Chan] = (*s.to_g, *s.from_g, *s.gpending)
        in_flight = sum(1 for c in chans for m in c if m == M_RMIG)
        return int(s.g_has_e[0]) + int(s.g_has_e[1]) + in_flight

    def state_invariants(self, st: State) -> tuple[str, ...]:
        assert isinstance(st, MigState)
        s = st
        out: list[str] = []
        copies = self._copies(s)
        if copies > 1:
            out.append(f"entity E duplicated: {copies} live copies")
        if copies == 0 and not s.crash_lost:
            out.append("entity E vanished with no crash to blame")
        return tuple(out)

    def terminal_violations(self, st: State) -> tuple[str, ...]:
        assert isinstance(st, MigState)
        s = st
        out: list[str] = []
        hosted_alive = any(s.g_has_e[i] and s.g_alive[i] for i in (0, 1))
        if not hosted_alive and not s.crash_lost:
            out.append("terminal state: E is not hosted by any live game")
        if s.route and not s.g_has_e[s.route - 1]:
            # Route hygiene: the entity table must never keep an entry
            # pointing at a game that does not host the entity — the
            # cold-boot purge (_handle_set_game_id:857-874) and the
            # game-down sweep (_handle_game_down:1410-1424) exist
            # precisely to keep this true.
            out.append(f"terminal state: stale routing-table entry — E "
                       f"routed to game{s.route} which does not host it")
        if any(M_RMIG in gp for gp in s.gpending):
            out.append("terminal state: REAL_MIGRATE(E) stuck in a "
                       "dispatcher buffer forever")
        if s.blocked and all(s.g_alive):
            out.append("terminal state: E's stream blocked forever with "
                       "both games alive")
        return tuple(out)


# --- the gate-generation model ----------------------------------------------


class GateGenState(NamedTuple):
    bindings: frozenset[tuple[str, int]]  # (clientid, gate generation)
    detach_chan: Chan   # dispatcher A -> game (the restart broadcast)
    connect_chan: Chan  # dispatcher B -> game (the new client's boot)
    c2_bound: bool


@dataclasses.dataclass(frozen=True)
class GateGenConfig:
    name: str = "gate_generation"
    valid_gen: int = 2
    mutants: frozenset[str] = frozenset()


class GateGenerationModel(Model):
    """A gate process restarts: its detach broadcast (naming the new
    generation as valid) races the new generation's first client boot on
    a DIFFERENT dispatcher link — the PR 9 cross-dispatcher ordering.
    Mirrors entity/game_client.py gate_gen + entity_manager
    .on_gate_disconnected(gateid, valid_gen)."""

    def __init__(self, cfg: GateGenConfig) -> None:
        self.cfg = cfg
        self.name = cfg.name

    def initial(self) -> GateGenState:
        return GateGenState(
            bindings=frozenset({("c1", 1)}),
            detach_chan=(("NOTIFY_GATE_DISCONNECTED",
                          str(self.cfg.valid_gen)),),
            connect_chan=(("NOTIFY_CLIENT_CONNECTED", "c2",
                           str(self.cfg.valid_gen)),),
            c2_bound=False,
        )

    def actions(self, st: State) -> list[Step]:
        assert isinstance(st, GateGenState)
        s = st
        steps: list[Step] = []
        if s.detach_chan:
            msg, rest = s.detach_chan[0], s.detach_chan[1:]
            valid = int(msg[1])
            viols: list[str] = []
            if "skip_gen_check" in self.cfg.mutants:
                dropped = s.bindings
            else:
                dropped = frozenset(b for b in s.bindings
                                    if b[1] != valid)
            for cid, gen in dropped:
                if gen == valid:
                    viols.append(
                        f"detach broadcast removed live binding "
                        f"({cid}, gen {gen}) of the VALID generation")
            steps.append(Step(
                f"game: detach gate bindings (valid gen {valid})",
                s._replace(bindings=s.bindings - dropped,
                           detach_chan=rest),
                tuple(viols)))
        if s.connect_chan:
            msg, rest = s.connect_chan[0], s.connect_chan[1:]
            cid, gen = msg[1], int(msg[2])
            steps.append(Step(
                f"game: bind client {cid} (gen {gen})",
                s._replace(bindings=s.bindings | {(cid, gen)},
                           connect_chan=rest, c2_bound=True)))
        return steps

    def terminal_violations(self, st: State) -> tuple[str, ...]:
        assert isinstance(st, GateGenState)
        s = st
        out: list[str] = []
        if ("c1", 1) in s.bindings:
            out.append("dead-generation binding (c1, gen 1) survived "
                       "the restart detach")
        if s.c2_bound and ("c2", self.cfg.valid_gen) not in s.bindings:
            out.append("valid-generation binding (c2) was detached")
        return tuple(out)


# --- the boot-during-link-flap model -----------------------------------------


class BootState(NamedTuple):
    link: str   # conn | grace | dead
    boot: str   # pending | buffered | served | dropped
    reconnects_left: int


@dataclasses.dataclass(frozen=True)
class BootConfig:
    name: str = "boot_flap"
    reconnects: int = 1
    mutants: frozenset[str] = frozenset()


class BootFlapModel(Model):
    """A client boot request arrives while every boot-capable game is
    mid-reconnect (dispatcher/service.py:985-1026): the request buffers
    for the grace window and retries each tick; only a window that
    lapses with no game drops it."""

    def __init__(self, cfg: BootConfig) -> None:
        self.cfg = cfg
        self.name = cfg.name

    def initial(self) -> BootState:
        return BootState(link=LINK_GRACE, boot="pending",
                         reconnects_left=self.cfg.reconnects)

    def actions(self, st: State) -> list[Step]:
        assert isinstance(st, BootState)
        s = st
        steps: list[Step] = []
        if s.boot == "pending":
            if s.link == LINK_CONN:
                steps.append(Step("dispatcher: boot served immediately",
                                  s._replace(boot="served")))
            elif "drop_boot_no_game" in self.cfg.mutants:
                steps.append(Step(
                    "dispatcher: no game -> boot DROPPED [mutant]",
                    s._replace(boot="dropped")))
            else:
                steps.append(Step(
                    "dispatcher: no game -> buffer boot for the grace "
                    "window (:995-1003)",
                    s._replace(boot="buffered")))
        if s.link == LINK_GRACE and s.reconnects_left:
            steps.append(Step(
                "game: reconnects within the grace window",
                s._replace(link=LINK_CONN,
                           reconnects_left=s.reconnects_left - 1)))
        if s.link == LINK_GRACE:
            steps.append(Step("dispatcher: grace window expires",
                              s._replace(link=LINK_DEAD)))
        if s.boot == "buffered" and s.link == LINK_CONN:
            steps.append(Step(
                "dispatcher: tick retry serves the buffered boot "
                "(:1012-1026)", s._replace(boot="served")))
        if s.boot == "buffered" and s.link == LINK_DEAD:
            steps.append(Step(
                "dispatcher: boot window lapsed with no game; dropped",
                s._replace(boot="dropped")))
        return steps

    def terminal_violations(self, st: State) -> tuple[str, ...]:
        assert isinstance(st, BootState)
        s = st
        if s.boot == "dropped" and s.link == LINK_CONN:
            return ("boot request dropped even though a game "
                    "reconnected — every boot must eventually be served",)
        if s.boot not in ("served", "dropped"):
            return (f"terminal state with boot still {s.boot!r}",)
        return ()


# --- entry points ------------------------------------------------------------


def tier1_configs() -> list[Model]:
    """The bounded configurations tier-1 explores exhaustively."""
    return [
        MigrateCrashModel(MigConfig()),
        MigrateCrashModel(MigConfig(name="migrate_unknown_target",
                                    target_unregistered=True)),
        # the crashed target never comes back: grace expiry MUST bounce
        # the payload home (this is the config that exposes a widened
        # grace window — see the infinite_grace mutant)
        MigrateCrashModel(MigConfig(name="migrate_no_return",
                                    restarts=0)),
        GateGenerationModel(GateGenConfig()),
        BootFlapModel(BootConfig()),
    ]


def deep_configs() -> list[Model]:
    """Wider bounds for the slow suite: more crash/restart/sync budget
    around the same machine."""
    return [
        MigrateCrashModel(MigConfig(
            name="migrate_crash_deep", crashes=2, restarts=2, syncs=2,
            cancels=1)),
        MigrateCrashModel(MigConfig(
            name="migrate_unknown_deep", target_unregistered=True,
            crashes=1, restarts=2, syncs=2)),
    ]


def check_all(models: Iterable[Model],
              max_states: int = 1_000_000) -> list[CheckResult]:
    return [explore(m, max_states=max_states) for m in models]


def main(argv: Optional[list[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="exhaustive cluster-protocol model checker")
    ap.add_argument("--deep", action="store_true",
                    help="also run the slow-suite configurations")
    args = ap.parse_args(argv)
    models = tier1_configs() + (deep_configs() if args.deep else [])
    rc = 0
    for result in check_all(models):
        print(result.render())
        if not result.ok:
            rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())

"""gwlint core: file model, suppression mechanics, baseline, runner.

The engine is deliberately self-contained (ast + stdlib only — the image
has no tomllib/tomli, so the baseline file is read by a minimal TOML-subset
parser below).  Rules live in rules.py; this module owns everything a rule
needs to report a finding and everything the gate needs to decide whether
a finding is suppressed:

- **Inline pragma**: ``# gwlint: ok R3 reason text`` on the offending line
  suppresses that rule there.  A pragma without a reason does NOT count —
  the whole point is that every suppression is justified in-place.
- **Baseline** (``gwlint_baseline.toml``): entries keyed by
  ``(rule, path, symbol)`` — symbol is the dotted enclosing scope, e.g.
  ``SlabStore.pack_sync`` or ``<module>`` — each with a mandatory
  ``reason``.  Symbol keys (not line numbers) keep the baseline stable
  across unrelated edits.  ``run_lint`` reports stale entries so the
  baseline only ever shrinks outside review.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import tokenize
from typing import Iterable, Optional

#: Rules shipped with the engine (rules.py registers one checker per id).
RULE_IDS = ("R1", "R2", "R3", "R4", "R5", "R6", "R7")

_PRAGMA_RE = re.compile(r"#\s*gwlint:\s*ok\s+(R\d)\b[\s:,\u2014-]*(.*)")


@dataclasses.dataclass
class Violation:
    rule: str
    path: str  # repo-relative posix path
    line: int
    symbol: str  # dotted enclosing scope ("<module>" at module level)
    message: str

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] {self.symbol}: "
                f"{self.message}")


@dataclasses.dataclass
class Suppression:
    rule: str
    path: str
    symbol: str  # "" or "*" matches any symbol in the file
    reason: str
    used: int = 0

    def matches(self, v: Violation) -> bool:
        if self.rule != v.rule or self.path != v.path:
            return False
        return self.symbol in ("", "*") or self.symbol == v.symbol


class ParsedModule:
    """One source file: AST + raw lines + inline-pragma map."""

    def __init__(self, root: str, path: str) -> None:
        self.abspath = path
        self.path = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, "rb") as f:
            raw = f.read()
        self.source = raw.decode("utf-8", errors="replace")
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=self.path)
        # line -> {rule: reason} from "# gwlint: ok RN reason" comments.
        self.pragmas: dict[int, dict[str, str]] = {}
        self._scan_pragmas(raw)
        self._scopes: Optional[list[tuple[int, int, str]]] = None

    def _scan_pragmas(self, raw: bytes) -> None:
        try:
            tokens = tokenize.tokenize(iter(raw.splitlines(True)).__next__)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _PRAGMA_RE.search(tok.string)
                if m and m.group(2).strip():
                    self.pragmas.setdefault(tok.start[0], {})[
                        m.group(1)] = m.group(2).strip()
        except tokenize.TokenError:
            pass  # half-written file: pragma scan is best-effort

    # -- symbol attribution --------------------------------------------------

    def _build_scopes(self) -> list[tuple[int, int, str]]:
        spans: list[tuple[int, int, str]] = []

        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    name = f"{prefix}.{child.name}" if prefix else child.name
                    end = getattr(child, "end_lineno", child.lineno)
                    spans.append((child.lineno, end or child.lineno, name))
                    visit(child, name)
                else:
                    visit(child, prefix)

        visit(self.tree, "")
        return spans

    def symbol_at(self, line: int) -> str:
        """Dotted enclosing def/class scope of a line (innermost wins)."""
        if self._scopes is None:
            self._scopes = self._build_scopes()
        best = "<module>"
        best_size = 1 << 30
        for lo, hi, name in self._scopes:
            if lo <= line <= hi and (hi - lo) < best_size:
                best, best_size = name, hi - lo
        return best

    def violation(self, rule: str, node_or_line, message: str) -> Violation:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Violation(rule, self.path, line, self.symbol_at(line), message)


# --- baseline: minimal TOML subset ------------------------------------------
#
# The image ships neither tomllib (py3.10) nor tomli, so the baseline is
# parsed here.  Accepted grammar — exactly what the writer below emits:
#   [[suppress]]
#   rule = "R3"
#   path = "goworld_tpu/netutil/rudp.py"
#   symbol = "RUDPConnection._on_segment"   # optional ("" / "*" = any)
#   reason = "why this is fine"
# Blank lines and full-line comments are ignored; values are basic strings.

_KEY_RE = re.compile(r'^([A-Za-z_][A-Za-z0-9_]*)\s*=\s*"((?:[^"\\]|\\.)*)"\s*(?:#.*)?$')


def _unescape(s: str) -> str:
    return (s.replace('\\"', '"').replace("\\\\", "\\")
            .replace("\\n", "\n").replace("\\t", "\t"))


def _escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def load_baseline(path: str) -> list[Suppression]:
    entries: list[Suppression] = []
    cur: Optional[dict[str, str]] = None

    def flush() -> None:
        nonlocal cur
        if cur is None:
            return
        missing = [k for k in ("rule", "path", "reason") if not cur.get(k)]
        if missing:
            raise ValueError(
                f"{path}: [[suppress]] entry at end of block missing "
                f"required key(s) {missing} — every suppression needs a "
                f"rule, a path, and a non-empty justification")
        entries.append(Suppression(cur["rule"], cur["path"],
                                   cur.get("symbol", ""), cur["reason"]))
        cur = None

    with open(path, encoding="utf-8") as f:
        for ln, rawline in enumerate(f, 1):
            line = rawline.strip()
            if not line or line.startswith("#"):
                continue
            if line == "[[suppress]]":
                flush()
                cur = {}
                continue
            m = _KEY_RE.match(line)
            if m:
                if cur is None:
                    raise ValueError(
                        f"{path}:{ln}: key outside a [[suppress]] block")
                cur[m.group(1)] = _unescape(m.group(2))
                continue
            raise ValueError(f"{path}:{ln}: unparseable line {line!r} "
                             f"(gwlint reads a strict TOML subset)")
    flush()
    return entries


def format_baseline(entries: Iterable[Suppression]) -> str:
    out = ["# gwlint suppression baseline — every entry records ONE known",
           "# violation with a justification.  The tier-1 gate fails on any",
           "# violation NOT matched here, so this file only changes in",
           "# review: fix the finding, or add an entry explaining why not.",
           ""]
    for e in entries:
        out.append("[[suppress]]")
        out.append(f'rule = "{_escape(e.rule)}"')
        out.append(f'path = "{_escape(e.path)}"')
        if e.symbol:
            out.append(f'symbol = "{_escape(e.symbol)}"')
        out.append(f'reason = "{_escape(e.reason)}"')
        out.append("")
    return "\n".join(out)


# --- runner -----------------------------------------------------------------


@dataclasses.dataclass
class LintResult:
    violations: list[Violation]  # unsuppressed
    suppressed: list[Violation]
    stale_baseline: list[Suppression]
    modules: list[ParsedModule]

    @property
    def ok(self) -> bool:
        return not self.violations

    def render(self) -> str:
        lines = [v.render() for v in self.violations]
        lines.append(f"gwlint: {len(self.violations)} violation(s), "
                     f"{len(self.suppressed)} suppressed, "
                     f"{len(self.stale_baseline)} stale baseline entrie(s)")
        for s in self.stale_baseline:
            lines.append(f"  stale baseline: {s.rule} {s.path} "
                         f"{s.symbol or '*'} ({s.reason})")
        return "\n".join(lines)


def iter_py_files(root: str, subdir: str) -> list[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(os.path.join(root, subdir)):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return out


def parse_package(root: str, subdirs: Iterable[str] = ("goworld_tpu",)
                  ) -> list[ParsedModule]:
    return [ParsedModule(root, p)
            for sub in subdirs for p in iter_py_files(root, sub)]


def run_lint(root: str, baseline_path: Optional[str] = None,
             rules: Optional[Iterable[str]] = None,
             modules: Optional[list[ParsedModule]] = None) -> LintResult:
    """Lint ``goworld_tpu/`` under ``root`` and fold in suppressions."""
    from goworld_tpu.analysis import rules as rules_mod

    if modules is None:
        modules = parse_package(root)
    active = tuple(rules) if rules is not None else RULE_IDS
    raw: list[Violation] = []
    for rid in active:
        raw.extend(rules_mod.CHECKERS[rid](modules, root))

    baseline = load_baseline(baseline_path) if baseline_path else []
    unsuppressed: list[Violation] = []
    suppressed: list[Violation] = []
    for v in sorted(raw, key=lambda v: (v.path, v.line, v.rule)):
        mod_pragmas = next((m.pragmas for m in modules if m.path == v.path),
                           {})
        if v.rule in mod_pragmas.get(v.line, {}):
            suppressed.append(v)
            continue
        hit = next((s for s in baseline if s.matches(v)), None)
        if hit is not None:
            hit.used += 1
            suppressed.append(v)
        else:
            unsuppressed.append(v)
    stale = [s for s in baseline if not s.used]
    return LintResult(unsuppressed, suppressed, stale, modules)

"""Runtime lock-order detector: the dynamic complement to gwlint R4.

``LockGraphMonitor`` wraps ``threading.Lock``/``RLock`` construction so
every lock created while installed is tracked: each acquisition records
directed edges from every lock the acquiring thread already holds to the
new one, keyed by the lock's *creation site* (file:line) so all
instances born at one callsite collapse into a single graph node — that
is what turns "thread A took slab-lock then ring-lock, thread B the
reverse" into a visible AB/BA cycle even when the instances differ.  It
also patches ``time.sleep`` and ``queue.Queue.get/put`` to record any
blocking call made while a tracked lock is held — the game-loop /
storage-worker / network-thread interleavings PRs 3–4 debugged by hand.

Scope and honesty notes:

- Only locks constructed while installed are tracked; module-level locks
  created at import time are invisible.  Tier-1 therefore installs the
  monitor BEFORE building the cluster under test.
- Edges between two *different* instances from the same creation site
  ("peer" edges, e.g. two Counter ring locks) are recorded but excluded
  from the cycle assertion: same-site nesting is usually a benign
  container-of-children pattern, while a true same-INSTANCE re-acquire
  of a non-reentrant lock is reported immediately as a deadlock.
- The monitor never blocks the program: bookkeeping is a thread-local
  list plus one small mutex around the shared edge set.

Usage (see tests/test_analysis.py)::

    mon = LockGraphMonitor()
    with mon.installed():
        ... build + run the cluster ...
    report = mon.report()
    assert not report["cycles"] and not report["blocking"]
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
from contextlib import contextmanager
from typing import Any, Iterator, Optional

_real_lock_ctor = threading.Lock
_real_rlock_ctor = threading.RLock
_real_sleep = time.sleep
_real_queue_get = queue.Queue.get
_real_queue_put = queue.Queue.put


def _site_name(filename: str, lineno: int) -> str:
    """Short stable site id: last 3 path components + line (bare
    basenames collide — gate/service.py vs dispatcher/service.py)."""
    return f"{'/'.join(filename.split('/')[-3:])}:{lineno}"


def _creation_site() -> tuple[str, bool]:
    """(site, engine_owned) of the frame that constructed the lock —
    first frame outside this module and the threading machinery.
    engine_owned marks locks born in goworld_tpu code, so the tier-1
    assertions can scope to locks we own rather than jax/stdlib
    internals created while the monitor happened to be installed."""
    for frame in reversed(traceback.extract_stack()):
        fn = frame.filename
        if fn.endswith("lockgraph.py") or fn.endswith("threading.py"):
            continue
        return _site_name(fn, frame.lineno), "goworld_tpu" in fn
    return "<unknown>", False


class _TrackedLock:
    """Duck-type of threading.Lock/RLock good enough for `with`,
    Condition wrapping, and bare acquire/release."""

    def __init__(self, monitor: "LockGraphMonitor", inner: Any,
                 site: str, reentrant: bool) -> None:
        self._monitor = monitor
        self._inner = inner
        self.site = site
        self.reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._monitor._before_acquire(self, blocking)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._monitor._after_acquire(self)
        return got

    def release(self) -> None:
        self._monitor._on_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    # RLock internals used by threading.Condition
    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        return self._inner.locked()

    def __repr__(self) -> str:
        return f"<TrackedLock {self.site} reentrant={self.reentrant}>"


class LockGraphMonitor:
    """Records the cross-thread lock acquisition-order graph plus
    blocking-calls-under-lock while installed."""

    def __init__(self) -> None:
        self._mu = _real_lock_ctor()
        self._tls = threading.local()
        # (site_a, site_b) -> count, for a held when b acquired
        self.edges: dict[tuple[str, str], int] = {}
        # same-site different-instance nestings (excluded from cycles)
        self.peer_edges: dict[str, int] = {}
        self.sites: dict[str, int] = {}  # site -> locks created there
        self.goworld_sites: set[str] = set()  # sites in goworld_tpu code
        self.blocking: list[dict] = []  # blocking call under held lock
        self.deadlocks: list[dict] = []  # same-instance re-acquire
        self._installed = False

    # -- bookkeeping ---------------------------------------------------------

    def _held(self) -> list[_TrackedLock]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _before_acquire(self, lock: _TrackedLock, blocking: bool) -> None:
        # Tracked locks outlive uninstall() inside long-lived components;
        # only RECORD while installed (held bookkeeping stays on so the
        # per-thread stacks remain balanced either way).
        held = self._held()
        if not held or not self._installed:
            return
        if blocking and not lock.reentrant and any(
                h._inner is lock._inner for h in held):
            with self._mu:
                self.deadlocks.append({
                    "site": lock.site,
                    "thread": threading.current_thread().name,
                    "held": [h.site for h in held],
                    "stack": traceback.format_stack(limit=8),
                })
        with self._mu:
            for h in held:
                if h._inner is lock._inner:
                    continue
                if h.site == lock.site:
                    self.peer_edges[h.site] = \
                        self.peer_edges.get(h.site, 0) + 1
                else:
                    key = (h.site, lock.site)
                    self.edges[key] = self.edges.get(key, 0) + 1

    def _after_acquire(self, lock: _TrackedLock) -> None:
        self._held().append(lock)

    def _on_release(self, lock: _TrackedLock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    def _on_blocking(self, what: str) -> None:
        held = self._held()
        if not held or not self._installed:
            return
        site = "<unknown>"
        for frame in reversed(traceback.extract_stack()):
            fn = frame.filename
            if fn.endswith(("lockgraph.py", "threading.py", "queue.py")):
                continue
            site = _site_name(fn, frame.lineno)
            break
        with self._mu:
            self.blocking.append({
                "call": what,
                "site": site,
                "thread": threading.current_thread().name,
                "held": [h.site for h in held],
            })

    # -- installation --------------------------------------------------------

    def _make_lock(self) -> _TrackedLock:
        site, gw = _creation_site()
        with self._mu:
            self.sites[site] = self.sites.get(site, 0) + 1
            if gw:
                self.goworld_sites.add(site)
        return _TrackedLock(self, _real_lock_ctor(), site, reentrant=False)

    def _make_rlock(self) -> _TrackedLock:
        site, gw = _creation_site()
        with self._mu:
            self.sites[site] = self.sites.get(site, 0) + 1
            if gw:
                self.goworld_sites.add(site)
        return _TrackedLock(self, _real_rlock_ctor(), site, reentrant=True)

    def install(self) -> None:
        if self._installed:
            return
        self._installed = True
        monitor = self

        threading.Lock = monitor._make_lock  # type: ignore[assignment]
        threading.RLock = monitor._make_rlock  # type: ignore[assignment]

        def traced_sleep(secs: float) -> None:
            if secs > 0:
                monitor._on_blocking(f"time.sleep({secs!r})")
            _real_sleep(secs)

        def traced_get(self: queue.Queue, block: bool = True,
                       timeout: Optional[float] = None):
            if block and timeout != 0:
                monitor._on_blocking("queue.Queue.get(block=True)")
            return _real_queue_get(self, block, timeout)

        def traced_put(self: queue.Queue, item: Any, block: bool = True,
                       timeout: Optional[float] = None):
            if block and timeout != 0 and self.maxsize > 0:
                monitor._on_blocking("queue.Queue.put(block=True)")
            return _real_queue_put(self, item, block, timeout)

        time.sleep = traced_sleep  # type: ignore[assignment]
        queue.Queue.get = traced_get  # type: ignore[method-assign]
        queue.Queue.put = traced_put  # type: ignore[method-assign]

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        threading.Lock = _real_lock_ctor  # type: ignore[assignment]
        threading.RLock = _real_rlock_ctor  # type: ignore[assignment]
        time.sleep = _real_sleep  # type: ignore[assignment]
        queue.Queue.get = _real_queue_get  # type: ignore[method-assign]
        queue.Queue.put = _real_queue_put  # type: ignore[method-assign]

    @contextmanager
    def installed(self) -> Iterator["LockGraphMonitor"]:
        self.install()
        try:
            yield self
        finally:
            self.uninstall()

    # -- analysis ------------------------------------------------------------

    def find_cycles(self, goworld_only: bool = False) -> list[list[str]]:
        """Cycles in the site-level acquisition-order graph (iterative
        DFS with an explicit stack; peer edges excluded by construction).
        ``goworld_only`` restricts the graph to edges between locks the
        engine itself created — the tier-1 assertion surface."""
        with self._mu:
            adj: dict[str, set[str]] = {}
            for (a, b) in self.edges:
                if goworld_only and not (a in self.goworld_sites
                                         and b in self.goworld_sites):
                    continue
                adj.setdefault(a, set()).add(b)
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in adj}
        cycles: list[list[str]] = []

        def dfs(start: str) -> None:
            stack: list[tuple[str, Iterator[str]]] = [
                (start, iter(adj.get(start, ())))]
            color[start] = GRAY
            path = [start]
            while stack:
                node, it = stack[-1]
                advanced = False
                for nxt in it:
                    c = color.get(nxt, WHITE)
                    if c == GRAY:
                        i = path.index(nxt)
                        cycles.append(path[i:] + [nxt])
                    elif c == WHITE:
                        color[nxt] = GRAY
                        stack.append((nxt, iter(adj.get(nxt, ()))))
                        path.append(nxt)
                        advanced = True
                        break
                if not advanced:
                    color[node] = BLACK
                    stack.pop()
                    path.pop()

        for n in list(adj):
            if color.get(n, WHITE) == WHITE:
                dfs(n)
        return cycles

    def report(self) -> dict:
        with self._mu:
            edges = dict(self.edges)
            peers = dict(self.peer_edges)
            blocking = list(self.blocking)
            deadlocks = list(self.deadlocks)
            sites = dict(self.sites)
        return {
            "locks_created": sum(sites.values()),
            "sites": sites,
            "goworld_sites": sorted(self.goworld_sites),
            "edges": {f"{a} -> {b}": n for (a, b), n in sorted(edges.items())},
            "peer_edges": peers,
            "cycles": self.find_cycles(),
            "goworld_cycles": self.find_cycles(goworld_only=True),
            "goworld_blocking": [
                b for b in blocking
                if any(h in self.goworld_sites for h in b["held"])],
            "blocking": blocking,
            "deadlocks": deadlocks,
        }

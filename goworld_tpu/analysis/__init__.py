"""Engine-aware static analysis (gwlint) + runtime lock-order detection.

Two halves, one goal — turn the hand-written invariants the test oracles
keep re-discovering into machine-checked properties:

- ``gwlint`` (core.py + rules.py): an AST rule engine run over the whole
  package by tier-1 (``tools/gwlint.py`` locally).  Seven engine-specific
  rules — jit hygiene, hot-path shape, parse bounds, lock discipline,
  telemetry hygiene, config-key drift, and wire-proto conformance
  against the declarative schema in proto/schema.py (R7, with a pinned
  schema digest per PROTO_VERSION) — plus a symbol-reachability pass
  for dead code.  Violations are suppressed only through the committed
  ``gwlint_baseline.toml`` (every entry carries a justification) or an
  inline ``# gwlint: ok RN reason`` pragma, so the gate starts green and
  *ratchets*: new code can only add violations by editing the baseline
  in review.
- ``lockgraph``: an opt-in instrumented Lock wrapper recording the
  cross-thread acquisition-order graph at runtime (the dynamic
  complement to rule R4), asserted acyclic — and free of blocking calls
  under a held lock — by tier-1 over the chaos and stress smokes.
- ``modelcheck``: an explicit-state model checker for the cluster
  protocol — the dispatcher/game/gate state machines (migrate target
  states, grace windows, sync parking, boot buffering, gate-binding
  generations) explored exhaustively over bounded interleavings of
  delivery, crash, cold restart and grace expiry, asserting the PR-9
  zero-loss invariants; failing interleavings print as readable message
  traces.  The model is the spec future protocol PRs extend first.
"""

from goworld_tpu.analysis.core import (
    Violation,
    LintResult,
    load_baseline,
    run_lint,
)
from goworld_tpu.analysis.lockgraph import LockGraphMonitor


def hot_path(fn):
    """Mark a function as being on a per-tick hot path.

    gwlint's R2 (hot-path shape) checks every function carrying this
    decorator — beside the config-listed allowset in rules.py — for
    per-item Python loops over entity-sized iterables and per-record
    ``struct.pack``.  Runtime cost: one attribute write at import.
    """
    fn.__gwlint_hot_path__ = True
    return fn


__all__ = [
    "Violation",
    "LintResult",
    "load_baseline",
    "run_lint",
    "LockGraphMonitor",
    "hot_path",
]

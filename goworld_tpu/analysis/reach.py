"""Symbol reachability: dead module-level defs and unused imports.

This is gwlint's janitorial pass (``tools/gwlint.py --dead-code``), NOT a
gating rule: name-based reachability over a dynamic codebase is
conservative in one direction only (a reported symbol really has no
textual reference anywhere), so findings are reviewed and deleted by a
human, not failed by CI.  References are gathered from the package plus
every caller surface that legitimately reaches into it: tests/, tools/,
bench.py, examples/, and the graft entry point.

A module-level def counts as referenced if its bare name appears
anywhere outside its own definition as a Name load, an attribute access,
or inside a string literal (getattr-by-name, entity-class registration
and RPC dispatch all go through strings in this engine).  ``__dunder__``
names, ``main``, and anything exported via ``__all__`` are always kept.
An import is unused if the bound alias has no Load/attribute use in its
module — except in ``__init__.py`` files, where imports ARE the export
surface.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

from goworld_tpu.analysis.core import ParsedModule, iter_py_files

#: caller surfaces outside the package whose references keep symbols alive
EXTRA_ROOTS = ("tests", "tools", "examples")
EXTRA_FILES = ("bench.py", "__graft_entry__.py")


@dataclasses.dataclass
class DeadSymbol:
    path: str
    line: int
    name: str
    kind: str  # "function" | "class" | "import"

    def render(self) -> str:
        return f"{self.path}:{self.line}: unreferenced {self.kind} {self.name!r}"


def _string_words(tree: ast.AST) -> set[str]:
    words: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            words.update(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", node.value))
    return words


def _referenced_names(tree: ast.AST) -> set[str]:
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            names.add(node.id)
        elif isinstance(node, ast.Attribute):
            names.add(node.attr)
    return names


def find_dead_code(root: str, modules: list[ParsedModule]
                   ) -> list[DeadSymbol]:
    # one global reference pool: package + caller surfaces
    refs: set[str] = set()
    strings: set[str] = set()
    all_sources: list[tuple[str, ast.AST]] = [
        (m.path, m.tree) for m in modules]
    for sub in EXTRA_ROOTS:
        if os.path.isdir(os.path.join(root, sub)):
            for path in iter_py_files(root, sub):
                try:
                    pm = ParsedModule(root, path)
                except SyntaxError:
                    continue
                all_sources.append((pm.path, pm.tree))
    for fn in EXTRA_FILES:
        p = os.path.join(root, fn)
        if os.path.exists(p):
            try:
                pm = ParsedModule(root, p)
            except SyntaxError:
                continue
            all_sources.append((pm.path, pm.tree))
    # precompute per-source reference/string sets ONCE — recomputing them
    # per candidate symbol is quadratic over the repo (≈100 s vs ≈1 s)
    per_source: dict[str, tuple[set[str], set[str]]] = {
        p: (_referenced_names(tree), _string_words(tree))
        for p, tree in all_sources}
    for names, words in per_source.values():
        refs |= names
        strings |= words

    out: list[DeadSymbol] = []
    for mod in modules:
        exported: set[str] = set()
        for stmt in mod.tree.body:
            if (isinstance(stmt, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "__all__"
                            for t in stmt.targets)
                    and isinstance(stmt.value, (ast.List, ast.Tuple))):
                exported.update(
                    e.value for e in stmt.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str))
        # per-module reference pools, computed once (per-symbol ast.walk
        # sweeps made this pass quadratic over the repo)
        name_counts: dict[str, int] = {}
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Name):
                name_counts[n.id] = name_counts.get(n.id, 0) + 1
        attr_uses = {n.attr for n in ast.walk(mod.tree)
                     if isinstance(n, ast.Attribute)}
        mod_strings = per_source[mod.path][1]
        # dead module-level defs: name referenced nowhere but its def
        for stmt in mod.tree.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                continue
            name = stmt.name
            if (name.startswith("__") or name == "main"
                    or name in exported
                    or (stmt.lineno <= len(mod.lines)
                        and "gwlint: keep" in mod.lines[stmt.lineno - 1])):
                continue
            # the def binds no Name node for itself, so any Name/attr
            # occurrence is a real reference
            referenced_locally = (name_counts.get(name, 0) > 0
                                  or name in attr_uses)
            external = any(
                name in names or name in words
                for p, (names, words) in per_source.items()
                if p != mod.path)
            if not referenced_locally and not external and \
                    name not in strings:
                kind = ("class" if isinstance(stmt, ast.ClassDef)
                        else "function")
                out.append(DeadSymbol(mod.path, stmt.lineno, name, kind))
        # unused imports (skip __init__.py: imports are the API there)
        if mod.path.endswith("__init__.py"):
            continue
        for node in ast.walk(mod.tree):
            names: list[tuple[str, int]] = []
            if isinstance(node, ast.Import):
                for a in node.names:
                    bound = a.asname or a.name.split(".")[0]
                    names.append((bound, node.lineno))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue  # compiler directive, not a binding to use
                for a in node.names:
                    if a.name == "*":
                        continue
                    names.append((a.asname or a.name, node.lineno))
            for bound, line in names:
                if bound.startswith("_"):
                    continue
                if bound in exported:
                    continue
                # a Name load, attribute use, or annotation string use
                kept = line <= len(mod.lines) and \
                    "gwlint: keep" in mod.lines[line - 1]
                if (name_counts.get(bound, 0) == 0
                        and bound not in attr_uses
                        and bound not in mod_strings
                        and not kept):
                    out.append(DeadSymbol(mod.path, line, bound, "import"))
    return out

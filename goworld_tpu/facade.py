"""Public API facade.

Reference parity: ``goworld.go:17-256`` — the single module game developers
import: Run, RegisterEntity/Space/Service, CreateSpace*/CreateEntity*/
LoadEntity*, Call/CallService*/CallNilSpaces, KVDB helpers, timers, crontab.

Symbols are re-exported lazily so that importing ``goworld_tpu`` never drags
in networking or JAX until used.
"""

from __future__ import annotations

from goworld_tpu.common import (  # noqa: F401
    EntityID,
    ClientID,
    gen_entity_id,
    gen_fixed_entity_id,
)

# goworld.go symbol → (module, attr). Names follow the reference's facade
# (snake_cased); each maps to the subsystem that implements it.
_LAZY: dict[str, tuple[str, str]] = {
    # process entry points (goworld.Run → game.Run, goworld.go:34)
    "run": ("goworld_tpu.game", "run"),
    "run_gate": ("goworld_tpu.gate", "run"),
    "run_dispatcher": ("goworld_tpu.dispatcher", "run"),
    # types
    "Entity": ("goworld_tpu.entity.entity", "Entity"),
    "Space": ("goworld_tpu.entity.space", "Space"),
    "Vector3": ("goworld_tpu.entity.vector", "Vector3"),
    "GameClient": ("goworld_tpu.entity.game_client", "GameClient"),
    # registration (goworld.go:44-76)
    "register_entity": ("goworld_tpu.entity.entity_manager", "register_entity"),
    "register_space": ("goworld_tpu.entity.entity_manager", "register_space"),
    "register_service": ("goworld_tpu.service", "register_service"),
    # entity / space creation (goworld.go:78-140)
    "create_entity_locally": ("goworld_tpu.entity.entity_manager", "create_entity_locally"),
    "create_entity_somewhere": ("goworld_tpu.entity.entity_manager", "create_entity_somewhere"),
    "create_space_locally": ("goworld_tpu.entity.entity_manager", "create_space_locally"),
    "create_space_somewhere": ("goworld_tpu.entity.entity_manager", "create_space_somewhere"),
    "load_entity_locally": ("goworld_tpu.entity.entity_manager", "load_entity_locally"),
    "load_entity_somewhere": ("goworld_tpu.entity.entity_manager", "load_entity_somewhere"),
    "get_entity": ("goworld_tpu.entity.entity_manager", "get_entity"),
    "get_space": ("goworld_tpu.entity.entity_manager", "get_space"),
    "get_nil_space": ("goworld_tpu.entity.entity_manager", "get_nil_space"),
    "get_nil_space_id": ("goworld_tpu.entity.entity_manager", "get_nil_space_id"),
    "get_entities_by_type": ("goworld_tpu.entity.entity_manager", "get_entities_by_type"),
    "get_game_id": ("goworld_tpu.entity.entity_manager", "get_game_id"),
    "get_online_games": ("goworld_tpu.entity.entity_manager", "get_online_games"),
    "now": ("goworld_tpu.entity.entity_manager", "now"),
    # RPC (goworld.go:142-178)
    "call_entity": ("goworld_tpu.entity.entity_manager", "call_entity"),
    "call_nil_spaces": ("goworld_tpu.entity.entity_manager", "call_nil_spaces"),
    "call_service_any": ("goworld_tpu.service", "call_service_any"),
    "call_service_all": ("goworld_tpu.service", "call_service_all"),
    "call_service_shard_index": ("goworld_tpu.service", "call_service_shard_index"),
    "call_service_shard_key": ("goworld_tpu.service", "call_service_shard_key"),
    "get_service_entity_id": ("goworld_tpu.service", "get_service_entity_id"),
    "get_service_shard_count": ("goworld_tpu.service", "get_service_shard_count"),
    "check_service_entities_ready": ("goworld_tpu.service", "check_service_entities_ready"),
    # kvdb (goworld.go:200-232)
    "kvdb_get": ("goworld_tpu.kvdb", "get"),
    "kvdb_put": ("goworld_tpu.kvdb", "put"),
    "kvdb_get_or_put": ("goworld_tpu.kvdb", "get_or_put"),
    "kvdb_get_range": ("goworld_tpu.kvdb", "get_range"),
    # kvreg
    "kvreg_register": ("goworld_tpu.kvreg", "register"),
    "kvreg_get": ("goworld_tpu.kvreg", "get"),
    # storage
    "list_entity_ids": ("goworld_tpu.storage", "list_entity_ids"),
    "entity_storage_exists": ("goworld_tpu.storage", "exists"),
    # scheduling (goworld.go:236-256)
    "post": ("goworld_tpu.utils.post", "post"),
    "register_crontab": ("goworld_tpu.utils.crontab", "register"),
    # config
    "get_config": ("goworld_tpu.config", "get"),
    "set_config_file": ("goworld_tpu.config", "set_config_file"),
}

__all__ = [
    "EntityID",
    "ClientID",
    "gen_entity_id",
    "gen_fixed_entity_id",
    *_LAZY,
]


def __getattr__(name: str):
    if name in _LAZY:
        module, attr = _LAZY[name]
        import importlib

        mod = importlib.import_module(module)
        return getattr(mod, attr)
    raise AttributeError(f"module 'goworld_tpu' has no attribute {name!r}")

"""Public API facade.

Reference parity: ``goworld.go:17-256`` — the single module game developers
import: Run, RegisterEntity/Space/Service, CreateSpace*/CreateEntity*/
LoadEntity*, Call/CallService*/CallNilSpaces, KVDB helpers, timers, crontab.

This module grows as subsystems land; symbols are re-exported lazily so that
importing ``goworld_tpu`` never drags in networking or JAX until used.
"""

from __future__ import annotations

from goworld_tpu.common import (  # noqa: F401
    EntityID,
    ClientID,
    gen_entity_id,
    gen_fixed_entity_id,
)

__all__ = [
    "EntityID",
    "ClientID",
    "gen_entity_id",
    "gen_fixed_entity_id",
]


def __getattr__(name: str):
    # Lazy exports wired up as subsystems are implemented.
    if name in _LAZY:
        module, attr = _LAZY[name]
        import importlib

        mod = importlib.import_module(module)
        return getattr(mod, attr)
    raise AttributeError(f"module 'goworld_tpu' has no attribute {name!r}")


_LAZY: dict[str, tuple[str, str]] = {}

"""Scenario runner: one drive loop for every scenario on every engine.

``run_scenario(name, engine=...)`` makes two passes over the SAME seeded
world definition:

1. **verify pass** (untimed): the production pipelined ``step_async``
   loop with an interest-set oracle on the host — every enter must be
   fresh (not already interested, no duplicate within the tick), every
   leave must dissolve an existing pair, and the scenario's own
   ``observe()`` assertions run per tick.  A violation raises
   :class:`ScenarioInvariantError`; the headline never ships a number a
   wrong event stream produced.
2. **measure pass**: fresh world, same seed, best-of-``repeats`` timed
   pipelined runs (first step synchronous — compile + the enter storm —
   exactly like the pinned floor), yielding entity-updates/sec.

Engines: ``batched`` is the single-device ``NeighborEngine`` on the jnp
backend; ``sharded`` is the grid-strip ``SpatialShardedNeighborEngine``
on a forced multi-device CPU mesh (the caller must set
``XLA_FLAGS=--xla_force_host_platform_device_count=<shards>`` before the
first jax import — bench.py and the tests run this in a subprocess for
exactly that reason).  The scenario definition is identical either way;
only ``make_engine`` differs.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Set

import numpy as np

from goworld_tpu.scenarios import (
    ScenarioInvariantError,
    ScenarioSpec,
    ScenarioWorld,
    get_scenario,
)


class InterestOracle:
    """Host-side mirror of the engine's interest set, keyed by directed
    pair id ``watcher * n + subject``.  O(events) per tick — NOT O(n^2);
    the oracle scales with the stream it checks."""

    def __init__(self, n: int) -> None:
        self.n = n
        self.pairs: Set[int] = set()

    def _keys(self, events: np.ndarray) -> List[int]:
        if len(events) == 0:
            return []
        ev = np.asarray(events, np.int64)
        return (ev[:, 0] * self.n + ev[:, 1]).tolist()

    def apply(self, t: int, enters: np.ndarray, leaves: np.ndarray) -> None:
        ek, lk = self._keys(enters), self._keys(leaves)
        if len(set(ek)) != len(ek):
            raise ScenarioInvariantError(
                f"tick {t}: duplicate enter events within one tick")
        if len(set(lk)) != len(lk):
            raise ScenarioInvariantError(
                f"tick {t}: duplicate leave events within one tick")
        for k in lk:
            if k not in self.pairs:
                raise ScenarioInvariantError(
                    f"tick {t}: leave for pair ({k // self.n}, "
                    f"{k % self.n}) that was never entered")
            self.pairs.discard(k)
        for k in ek:
            if k in self.pairs:
                raise ScenarioInvariantError(
                    f"tick {t}: enter for pair ({k // self.n}, "
                    f"{k % self.n}) already interested")
            self.pairs.add(k)

    def check_alive(self, active: np.ndarray) -> None:
        """End-of-run: no surviving pair may reference a dead entity —
        deactivation must have drained its edges through leave events."""
        for k in self.pairs:
            a, b = k // self.n, k % self.n
            if not (active[a] and active[b]):
                raise ScenarioInvariantError(
                    f"stale interest pair ({a}, {b}) survives a dead "
                    f"entity — deactivation did not emit its leaves")


def make_engine(config: Dict[str, Any], engine: str) -> Any:
    """Build the AOI engine a scenario runs on. ``batched`` | ``sharded``."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from goworld_tpu.ops import NeighborEngine, NeighborParams

    params = NeighborParams(
        capacity=config.get("capacity", config["n"]),
        cell_size=config["cell_size"],
        grid_x=config["grid"], grid_z=config.get("grid_z", config["grid"]),
        space_slots=config["space_slots"],
        cell_capacity=config["cell_capacity"],
        max_events=config["max_events"],
    )
    if engine == "batched":
        return NeighborEngine(params, backend="jnp")
    if engine == "sharded":
        shards = int(config["shards"])
        if len(jax.devices()) < shards:
            raise RuntimeError(
                f"scenario engine 'sharded' needs {shards} devices but jax "
                f"sees {len(jax.devices())} — set XLA_FLAGS="
                f"--xla_force_host_platform_device_count={shards} before "
                "the first jax import (run in a fresh subprocess)")
        from goworld_tpu.parallel import make_mesh
        from goworld_tpu.parallel.spatial import SpatialShardedNeighborEngine

        return SpatialShardedNeighborEngine(
            params, make_mesh(shards), halo_cap=config.get("halo_cap"),
            prewarm_fallback=False)
    raise ValueError(f"unknown scenario engine {engine!r} "
                     "(batched | sharded)")


def _drive(world: ScenarioWorld, eng: Any,
           oracle: Optional[InterestOracle]) -> None:
    """The production pipelined loop: dispatch tick t while collecting
    tick t-1's events (diffs land one dispatch late by design,
    ops/neighbor.py). ``observe``/oracle attribution follows the pending
    step's tick, so assertions see the right world state."""
    eng.reset()
    ticks = int(world.config["ticks"])
    pending, prev_t = None, -1
    for t in range(ticks):
        dirty = True if t == 0 else world.tick(t)
        nxt = eng.step_async(world.pos, world.active, world.space,
                             world.radius, meta_dirty=bool(dirty))
        if pending is not None:
            e, l, d = pending.collect()
            if oracle is not None:
                oracle.apply(prev_t, e, l)
            world.observe(prev_t, e, l, int(d))
        pending, prev_t = nxt, t
    e, l, d = pending.collect()
    if oracle is not None:
        oracle.apply(prev_t, e, l)
    world.observe(prev_t, e, l, int(d))


def _retrace_count() -> int:
    from goworld_tpu.telemetry import sentinel

    return int(sentinel.steady_state_retraces())


# Every label a tick dispatch can launch its STEP under, across engines
# and backends.  The fallback decision on the spatial engine is made on
# the host BEFORE any launch (parallel/spatial.py step_async), so one
# dispatch fires exactly one of these — never two.  Paging drains
# (aoi_drain_*, *_drain_bits) are deliberately absent: a storm tick
# pages through extra drain launches by design, and the one-launch pin
# is about the step, not the overflow path.
_STEP_LABELS = tuple(
    f"aoi_step_{kind}{bk}"
    for kind in ("", "fused_", "tiered_", "verdict_")
    for bk in ("jnp", "pallas", "pallas_interpret")
) + (
    "sharded_step", "sharded_step_fused", "sharded_step_pallas",
    "spatial_step", "spatial_step_fused",
    "spatial_step_pallas", "spatial_step_pallas_fused",
)


def _step_launches() -> int:
    from goworld_tpu.telemetry import sentinel

    return int(sum(sentinel.launches_total(lb) for lb in _STEP_LABELS))


def run_scenario(name: str, engine: Optional[str] = "batched",
                 seed: Optional[int] = -1,
                 ticks_scale: Optional[float] = 1.0,
                 slo: Any = None) -> Dict[str, Any]:
    """Run a registered scenario end-to-end; returns the headline dict
    (bench.py prints it as the one JSON line).

    Passing ``None`` for engine/seed/ticks_scale consults the
    ``[scenario]`` ini section (ad-hoc/dev runs); the defaults (and
    bench.py's gate mode, which relies on them) never touch the ini, so
    committed floors cannot drift with an operator's config.  A negative
    seed — the default — means the registry's fixed per-scenario seed.

    ``slo`` is an optional :class:`SLOConfig`: when it has budgets set,
    the measure pass also records per-tick wall times and the run is
    judged against ``tick_p99_budget`` / ``steady_state_retraces`` —
    a violated budget raises :class:`SLOViolation` (the headline would
    have shipped a number the operator declared unacceptable). The
    per-tick clock reads happen ONLY under an active SLO gate, so the
    pinned floors' measure loop is untouched.

    The ``invariants`` sub-dict holds ONLY seed-deterministic fields —
    the determinism gate asserts two back-to-back runs produce it
    bit-identically.  Wall-clock numbers (value/runs/latencies) and
    engine-internal counters that may depend on timing live beside it.
    """
    if engine is None or ticks_scale is None or seed is None:
        from goworld_tpu.config import read_config

        sc = read_config.get().scenario
        if engine is None:
            engine = sc.default_engine
        if ticks_scale is None:
            ticks_scale = sc.ticks_scale
        if seed is None:
            seed = sc.seed
    if seed is not None and seed < 0:
        seed = None  # the registry's fixed per-scenario seed
    assert engine is not None and ticks_scale is not None
    spec: ScenarioSpec = get_scenario(name)
    retraces0 = _retrace_count()

    # Pass 1: verify — oracle + per-tick scenario assertions, untimed.
    world = spec.make(seed=seed, ticks_scale=ticks_scale)
    eng = make_engine(world.config, engine)
    world.setup()
    try:
        oracle = InterestOracle(world.cap)
        _drive(world, eng, oracle)
        oracle.check_alive(world.active)
        world.check_engine(eng, engine)
        invariants = world.invariants()
        extra = world.extra_headline()
    finally:
        world.teardown()

    # Pass 2: measure — fresh world, same seed, best-of-repeats timed.
    repeats = int(world.config.get("repeats", 1))
    ticks = int(world.config["ticks"])
    launches0 = _step_launches()
    slo_active = slo is not None and slo.enabled()
    tick_wall: List[float] = []
    runs: List[float] = []
    for _rep in range(repeats):
        w = spec.make(seed=seed, ticks_scale=ticks_scale)
        w.setup()
        try:
            eng.reset()
            # Sync first step: compile + the enter storm, off the clock
            # (the pinned-floor convention).
            eng.step(w.pos, w.active, w.space, w.radius)
            pending = None
            t0 = time.perf_counter()
            if slo_active:
                t_prev = t0
                for t in range(1, ticks):
                    dirty = w.tick(t)
                    nxt = eng.step_async(w.pos, w.active, w.space, w.radius,
                                         meta_dirty=bool(dirty))
                    if pending is not None:
                        pending.collect()
                    pending = nxt
                    now = time.perf_counter()
                    tick_wall.append(now - t_prev)
                    t_prev = now
            else:
                for t in range(1, ticks):
                    dirty = w.tick(t)
                    nxt = eng.step_async(w.pos, w.active, w.space, w.radius,
                                         meta_dirty=bool(dirty))
                    if pending is not None:
                        pending.collect()
                    pending = nxt
            if pending is not None:
                pending.collect()
            runs.append((ticks - 1) / (time.perf_counter() - t0) * w.n)
        finally:
            w.teardown()

    # One-launch pin (ISSUE 19): every measured tick must have cost
    # exactly one step launch — enter/leave storms, hotspot fallbacks
    # and strip re-plans included.  An extra launch means a hidden host
    # round-trip crept onto the steady path; a missing one means a tick
    # silently skipped the engine.  Hard gate, not a telemetry note.
    step_launches = _step_launches() - launches0
    ticks_dispatched = repeats * ticks
    if step_launches != ticks_dispatched:
        raise ScenarioInvariantError(
            f"one-launch pin violated: {ticks_dispatched} measured ticks "
            f"dispatched but {step_launches} step launches recorded")

    retraces = _retrace_count() - retraces0
    slo_verdict = None
    if slo_active:
        from goworld_tpu.telemetry.slo import (
            SLOViolation,
            judge_values,
            render_verdict,
        )

        s = sorted(tick_wall)
        tick_p99 = s[max(0, -(-len(s) * 99 // 100) - 1)] if s else 0.0
        slo_verdict = judge_values(
            slo, tick_p99=tick_p99, steady_state_retraces=retraces)
        if not slo_verdict["ok"]:
            raise SLOViolation(
                f"scenario {name!r} violated its SLO: "
                f"{render_verdict(slo_verdict)}")

    headline: Dict[str, Any] = {
        "metric": f"scenario_{name}_updates_per_sec",
        "value": round(max(runs), 1),
        "unit": "entity-updates/sec",
        "runs": [round(r, 1) for r in runs],
        "scenario": name,
        "engine": engine,
        "config": dict(spec.config),
        "seed": world.seed,
        "invariants": invariants,
        "steady_state_retraces": retraces,
        "step_launches": step_launches,
        "ticks_dispatched": ticks_dispatched,
        "one_launch_per_tick": True,
        "errors": 0,
    }
    if slo_verdict is not None:
        headline["slo"] = slo_verdict
    headline.update(extra)
    # Engine-internal counters: structural, but timing-adjacent on the
    # sharded tier (replan cadence), so they ride OUTSIDE invariants —
    # except the hotspot fallback count, which each scenario may choose
    # to pull INTO its invariants via engine_invariants().
    if engine == "sharded":
        headline["fallback_ticks"] = int(eng.total_fallbacks)
        headline["shard_migrations"] = int(eng.total_migrations)
        headline["fast_ticks"] = int(eng.total_fast_ticks)
    return headline

"""battle_royale: a shrinking zone forces mass enter waves while storm +
combat eliminations churn entities out of the world.

The zone is a disc centered on the world that shrinks linearly from
``zone_r0`` to ``zone_rf`` over the run.  Entities random-walk inside it;
anyone caught outside is pulled toward the center faster than the zone
shrinks AND takes storm damage (hp), so the far-corner population dies
early (the first churn wave) while everyone else is compressed into an
ever-denser endgame disc (the mass enter waves).  Combat eliminates a
fixed fraction of the living every tick down to an endgame floor — death
is deactivation, which must drain every interest edge through leave
events (the runner's oracle ``check_alive`` proves it, the engine-side
analog of slab quarantine).

Invariants: census conservation (alive + eliminated == n EVERY tick),
the alive trajectory sampled every 8 ticks, storm/combat kill split,
event totals, zero grid drops.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Tuple

import numpy as np

from goworld_tpu.scenarios import (
    ScenarioInvariantError,
    ScenarioSpec,
    ScenarioWorld,
    register,
)


def zone_radius(r0: float, rf: float, ticks: int, t: int) -> float:
    """The zone radius at tick ``t`` — linear shrink, clamped.  Pure so
    the chaos harness drives live avatars with the SAME zone math."""
    f = min(max(t / max(ticks - 1, 1), 0.0), 1.0)
    return r0 + (rf - r0) * f


def royale_ring_positions(n: int, t: int, ticks: int,
                          center: Tuple[float, float], r0: float,
                          rf: float) -> List[Tuple[float, float]]:
    """Deterministic per-entity positions on the shrinking zone's
    boundary ring (entity i at angle 2*pi*i/n, radius 0.8 * zone).  The
    chaos harness places its live ChaosAvatars with this: as the zone
    collapses everyone converges, producing the mass enter waves on real
    game-process AOI, with zero avatar destroys so the cluster census
    must stay exactly n_bots."""
    r = 0.8 * zone_radius(r0, rf, ticks, t)
    out = []
    for i in range(n):
        a = 2.0 * np.pi * i / max(n, 1)
        out.append((center[0] + r * float(np.cos(a)),
                    center[1] + r * float(np.sin(a))))
    return out


class BattleRoyaleWorld(ScenarioWorld):
    def __init__(self, config: Mapping[str, Any], seed: int) -> None:
        super().__init__(config, seed)
        self.pos = self.rng.uniform(
            0.0, self.world, (self.cap, 2)).astype(np.float32)
        self.center = np.array(
            [self.world / 2.0, self.world / 2.0], np.float32)
        self.r0 = float(config.get("zone_r0", self.world / 2.0))
        self.rf = float(config.get("zone_rf", self.world / 32.0))
        self.storm_speed = float(config.get("storm_speed", 60.0))
        # Pull starts at margin*zone so survivors ride WELL inside the
        # rim; damage only applies strictly outside the zone.  With the
        # zone shrinking ~31/tick and the pull at 60, only the far-corner
        # spawn population and unlucky rim-riders die to the storm.
        self.zone_margin = float(config.get("zone_margin", 0.7))
        self.walk_sigma = float(config.get("walk_sigma", 3.0))
        self.endgame_floor = int(config.get("endgame_floor", self.n // 16))
        self.hp = np.full(self.cap, int(config.get("hp", 12)), np.int32)
        self.alive_count = self.n
        self.storm_kills = 0
        self.combat_kills = 0
        self.alive_trajectory: List[int] = []

    def tick(self, t: int) -> bool:
        zone = np.float32(
            zone_radius(self.r0, self.rf, int(self.config["ticks"]), t))
        alive = self.active
        # Random walk + storm pull, vectorized (gwlint R2 hot path).
        step = self.rng.normal(
            0.0, self.walk_sigma, (self.cap, 2)).astype(np.float32)
        d = self.pos - self.center
        dist = np.maximum(np.hypot(d[:, 0], d[:, 1]), 1e-6).astype(np.float32)
        margin = zone * np.float32(self.zone_margin)
        pulled = alive & (dist > margin)
        outside = alive & (dist > zone)
        pull = np.minimum(np.float32(self.storm_speed),
                          dist - margin * np.float32(0.9))
        step -= np.where(pulled, pull / dist, np.float32(0.0))[:, None] * d
        # pos/active are REBOUND, never mutated in place: the previous
        # buffers may still back an in-flight step_async dispatch (the
        # runner pipelines), and racing it makes event streams
        # nondeterministic.
        self.pos = np.clip(
            self.pos + np.where(alive, np.float32(1.0),
                                np.float32(0.0))[:, None] * step,
            0.0, self.world)
        # Storm damage: hp drains outside the zone; 0 hp eliminates.
        self.hp -= outside.astype(np.int32)
        died_storm = alive & (self.hp <= 0)
        self.storm_kills += int(died_storm.sum())
        self.active = self.active & ~died_storm
        # Combat: a fixed fraction of the living falls every tick, down
        # to the endgame floor (keeps final density under cell_capacity).
        survivors = np.flatnonzero(self.active)
        kills = min(max(1, len(survivors) // 32),
                    max(0, len(survivors) - self.endgame_floor))
        died = died_storm.any() or kills > 0
        if kills > 0:
            fallen = self.rng.choice(survivors, kills, replace=False)
            self.active[fallen] = False
            self.combat_kills += kills
        self.alive_count = int(self.active.sum())
        # Census conservation — THE battle-royale invariant, every tick.
        if self.alive_count + self.storm_kills + self.combat_kills != self.n:
            raise ScenarioInvariantError(
                f"tick {t}: census broken — alive {self.alive_count} + "
                f"storm {self.storm_kills} + combat {self.combat_kills} "
                f"!= {self.n}")
        if t % 8 == 0:
            self.alive_trajectory.append(self.alive_count)
        return bool(died)

    def invariants(self) -> Dict[str, Any]:
        inv = super().invariants()
        inv.update({
            "alive_final": self.alive_count,
            "alive_trajectory": list(self.alive_trajectory),
            "storm_kills": self.storm_kills,
            "combat_kills": self.combat_kills,
            "eliminated": self.storm_kills + self.combat_kills,
        })
        return inv


# FIXED config (floor-grade: never self-tuned). Geometry satisfies the
# sharded engine's constraints on the standard forced 8-device mesh:
# capacity % 64 == 0, max_events % 8 == 0, grid >= 4 * shards.
SPEC = register(ScenarioSpec(
    name="battle_royale",
    description=("shrinking zone: mass enter waves + death churn; census "
                 "conservation every tick, dead entities must drain all "
                 "interest edges"),
    config={
        "n": 2048, "capacity": 2560, "cell_size": 100.0, "grid": 64,
        "space_slots": 1, "cell_capacity": 64, "max_events": 32768,
        "shards": 8, "ticks": 96, "radius": 100.0, "repeats": 2,
        "seed": 16,
    },
    factory=BattleRoyaleWorld,
))

"""Scenario matrix: reproducible, seed-deterministic workloads (ISSUE 16).

Every committed floor before this package measured ONE workload shape —
mutually-interested bots on a uniform grid.  A scenario is a first-class
workload object instead: a movement model + entity lifecycle + interest
profile + per-tick assertions, built from a fixed config and a seed, so
bench.py (``--scenario <name>``), the chaos harness, and tests all drive
the SAME definition through one interface.

Contract:

- **Deterministic**: all world randomness flows through ONE
  ``np.random.default_rng(seed)`` stream drawn in tick order, so the same
  seed reproduces the identical trajectory — and therefore the identical
  invariant fields (census trajectory, event counts) — run over run.
  Wall-clock fields (updates/sec, latencies) are reported OUTSIDE the
  ``invariants`` dict for exactly this reason.
- **Engine-agnostic**: a scenario only exposes the epoch arrays the
  NeighborEngine family steps (``pos/active/space/radius``); the runner
  (``scenarios/runner.py``) drives it on the batched single-device engine
  or the spatially sharded one, unchanged.
- **Self-checking**: ``observe()`` runs per-tick assertions against the
  engine's event stream (the runner adds an interest-set oracle on top:
  no duplicate enter, no orphan leave); a violation raises
  :class:`ScenarioInvariantError` — the scenario is a correctness gate
  first and a throughput number second.

The three shipped scenarios (each registered at import):

- ``battle_royale`` — a shrinking zone forces mass enter waves toward the
  center while storm + combat eliminations churn entities out of the
  world (death = deactivation, the slab-quarantine analog).  Invariants:
  census conservation (alive + eliminated == n every tick), the alive
  trajectory, event totals, zero grid drops.
- ``service_heavy`` — chat/mail/ranking traffic routed by the service
  layer's ``shard_by_key`` over sharded service counters, every op
  persisted through the REAL storage worker while an injected outage
  opens the circuit breaker (storage/circuit.py) mid-run.  Invariants:
  exactly-once per-shard receipts, circuit observed OPEN then recovered,
  zero lost saves after the heal.
- ``hotspot`` — everyone converges on one small crowd disc: worst-case
  AOI density (max cell population near cell_capacity), the spatial
  engine's hotter-than-a-strip fallback (a whole population in one strip
  exceeds the per-shard row budget — exact all-gather ticks, counted),
  and tier-0-everything sync load.

Adding a scenario: subclass :class:`ScenarioWorld`, give it a module-level
``SPEC = ScenarioSpec(...)`` with a FIXED config (floors must be
comparable round over round, so configs are never self-tuned), call
``register(SPEC)``, and import the module here.  Keep ``tick()``
vectorized — the per-tick bodies are gwlint R2 hot paths.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Mapping, Tuple

import numpy as np


class ScenarioInvariantError(AssertionError):
    """A per-tick or end-of-run scenario invariant did not hold."""


class ScenarioWorld:
    """Base workload: seeded epoch arrays + the hooks the runner drives.

    Subclasses fill ``pos/active/space/radius`` in ``__init__`` from
    ``self.rng`` and advance them in ``tick()``.  ``space`` stays 0 and
    ``radius`` stays the config's uniform AOI radius unless a scenario
    overrides them.
    """

    def __init__(self, config: Mapping[str, Any], seed: int) -> None:
        self.config = dict(config)
        self.seed = int(seed)
        self.rng = np.random.default_rng(self.seed)
        n = int(config["n"])
        self.n = n
        # Engine capacity may exceed the population: the extra rows stay
        # permanently inactive (slot slack), which is what gives the
        # sharded tier per-strip row headroom over the all-active
        # average — without it every uniform all-active world sits
        # exactly at the per-shard budget and falls back on any
        # imbalance.  hotspot deliberately keeps the slack small enough
        # that the endgame crowd still overflows a strip.
        self.cap = int(config.get("capacity", n))
        self.world = float(config["grid"]) * float(config["cell_size"])
        self.world_z = (float(config.get("grid_z", config["grid"]))
                        * float(config["cell_size"]))
        self.pos = np.zeros((self.cap, 2), np.float32)
        self.active = np.zeros(self.cap, bool)
        self.active[:n] = True
        self.space = np.zeros(self.cap, np.int32)
        self.radius = np.full(
            self.cap, float(config.get("radius", config["cell_size"])),
            np.float32)
        # Event accounting every scenario shares (filled by observe()).
        self.enter_events = 0
        self.leave_events = 0
        self.dropped_total = 0

    # --- runner hooks -------------------------------------------------------

    def setup(self) -> None:
        """Acquire out-of-world resources (service_heavy: the storage
        worker + backend).  Paired with :meth:`teardown`."""

    def teardown(self) -> None:
        """Release whatever :meth:`setup` acquired."""

    def tick(self, t: int) -> bool:
        """Advance the world one tick; returns True when active/space/
        radius changed (the engine's ``meta_dirty`` flag — lifecycle
        churn), False when only positions moved."""
        raise NotImplementedError

    def check_engine(self, eng: Any, engine: str) -> None:
        """End-of-verify-pass assertions against the ENGINE's own
        counters (hotspot: the sharded tier must have taken the
        hotter-than-a-strip exact fallback).  Default: none."""

    def extra_headline(self) -> Dict[str, Any]:
        """Scenario-specific headline fields that are NOT deterministic
        (wall-clock latencies etc.) — merged beside, never inside, the
        ``invariants`` dict."""
        return {}

    def observe(self, t: int, enters: np.ndarray, leaves: np.ndarray,
                dropped: int) -> None:
        """Per-tick assertions over the engine's event stream for tick
        ``t`` (the runner's pipelined loop delivers them one dispatch
        late, correctly attributed).  Base: event totals + the shared
        zero-grid-drop clause."""
        self.enter_events += int(len(enters))
        self.leave_events += int(len(leaves))
        self.dropped_total += int(dropped)
        if dropped > int(self.config.get("max_dropped", 0)):
            raise ScenarioInvariantError(
                f"{type(self).__name__}: tick {t} dropped {dropped} "
                f"entities from the AOI grid (cell_capacity overflow) — "
                f"the scenario config must keep density under capacity")

    def invariants(self) -> Dict[str, Any]:
        """Deterministic end-of-run invariant fields (identical run over
        run for one seed — the determinism gate compares this dict)."""
        return {
            "enter_events": self.enter_events,
            "leave_events": self.leave_events,
            "dropped": self.dropped_total,
        }


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """A registered scenario: FIXED config + factory.

    ``config`` must carry at least ``n / cell_size / grid / space_slots /
    cell_capacity / max_events / ticks / repeats / seed / shards`` — the
    engine geometry the runner builds, never self-tuned (scenario floors
    follow the same comparable-by-construction rule as the pinned floor).
    """

    name: str
    description: str
    config: Mapping[str, Any]
    factory: Callable[[Mapping[str, Any], int], ScenarioWorld]

    def make(self, seed: int | None = None,
             ticks_scale: float = 1.0) -> ScenarioWorld:
        cfg = dict(self.config)
        if ticks_scale != 1.0:
            cfg["ticks"] = max(8, int(round(cfg["ticks"] * ticks_scale)))
        return self.factory(
            cfg, self.config["seed"] if seed is None else seed)


_REGISTRY: Dict[str, ScenarioSpec] = {}


def register(spec: ScenarioSpec) -> ScenarioSpec:
    if spec.name in _REGISTRY:
        raise ValueError(f"scenario {spec.name!r} already registered")
    _REGISTRY[spec.name] = spec
    return spec


def get_scenario(name: str) -> ScenarioSpec:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r} (available: "
            f"{', '.join(scenario_names())})") from None


def scenario_names() -> Tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


# Scenario modules self-register on import; keep these last.
from goworld_tpu.scenarios import battle_royale as battle_royale  # noqa: E402
from goworld_tpu.scenarios import hotspot as hotspot  # noqa: E402
from goworld_tpu.scenarios import service_heavy as service_heavy  # noqa: E402

"""hotspot: everyone converges on one small crowd disc — worst-case AOI
density by construction.

Each entity owns a personal target drawn uniformly (area-uniform, sqrt
radial sampling) inside a disc of radius ``crowd_r`` around the world
center and marches straight at it, then jitters in place.  The endgame is
the regime that breaks AOI engines: max cell population pushed toward
``cell_capacity`` (but provably under it — ``dropped == 0`` stays a hard
per-tick clause), nearly every surviving interest pair inside the tier-0
band (tier-0-everything sync load), and on the spatially sharded engine
the entire population lands in a handful of grid columns — hotter than
any strip's per-shard row budget, which MUST trip the engine's
``strip_overflow`` exact-fallback path (``check_engine`` asserts the
fallback count is non-zero; it is THE hotspot invariant on that tier).

No lifecycle churn: after the first dispatch every tick is
``meta_dirty=False``, so the batched tier stays on its packed fast path
while density does all the damage.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import numpy as np

from goworld_tpu.scenarios import (
    ScenarioInvariantError,
    ScenarioSpec,
    ScenarioWorld,
    register,
)


class HotspotWorld(ScenarioWorld):
    def __init__(self, config: Mapping[str, Any], seed: int) -> None:
        super().__init__(config, seed)
        self.pos = self.rng.uniform(
            0.0, self.world, (self.cap, 2)).astype(np.float32)
        center = np.array([self.world / 2.0, self.world / 2.0], np.float32)
        crowd_r = float(config.get("crowd_r", 200.0))
        # Area-uniform targets in the crowd disc.
        rr = crowd_r * np.sqrt(self.rng.uniform(0.0, 1.0, self.cap))
        th = self.rng.uniform(0.0, 2.0 * np.pi, self.cap)
        self.target = (center + np.stack(
            [rr * np.cos(th), rr * np.sin(th)], 1)).astype(np.float32)
        self.speed = float(config.get("speed", 80.0))
        self.jitter = float(config.get("jitter", 2.0))

    def tick(self, t: int) -> bool:
        # March at the personal target, overshoot-safe; jitter on arrival.
        d = self.target - self.pos
        dist = np.maximum(np.hypot(d[:, 0], d[:, 1]), 1e-6).astype(np.float32)
        step = np.minimum(np.float32(self.speed), dist) / dist
        # Rebind, don't mutate: the previous buffer may back an in-flight
        # pipelined dispatch.
        self.pos = np.clip(
            self.pos + step[:, None] * d + self.rng.normal(
                0.0, self.jitter, (self.cap, 2)).astype(np.float32),
            0.0, self.world)
        return False  # pure movement: no lifecycle churn after tick 0

    def check_engine(self, eng: Any, engine: str) -> None:
        if engine == "sharded" and int(eng.total_fallbacks) == 0:
            raise ScenarioInvariantError(
                "hotspot on the sharded engine took ZERO exact-fallback "
                "ticks — the whole population in one strip must exceed "
                "the per-shard row budget (strip_overflow); the crowd "
                "never formed or the fallback path regressed")

    def invariants(self) -> Dict[str, Any]:
        inv = super().invariants()
        # Final-density facts, computed from positions (deterministic).
        cell = float(self.config["cell_size"])
        gx = int(self.config["grid"])
        pop = self.pos[:self.n]  # the live population, not slack rows
        cx = np.clip((pop[:, 0] // cell).astype(np.int64), 0, gx - 1)
        cz = np.clip((pop[:, 1] // cell).astype(np.int64), 0, gx - 1)
        counts = np.bincount(cx * gx + cz, minlength=gx * gx)
        d = pop[:, None, :] - pop[None, :, :]
        d2 = (d * d).sum(-1)
        np.fill_diagonal(d2, np.inf)
        r = float(self.config["radius"])
        in_aoi = int((d2 < r * r).sum())
        in_tier0 = int((d2 < (0.5 * r) ** 2).sum())
        tier0_share = round(in_tier0 / max(in_aoi, 1), 4)
        avg_neighbors = round(in_aoi / self.n, 1)
        # 0.25 is the scale-free uniform-field limit for the 0.5*radius
        # tier-0 band; beating it means the crowd genuinely saturates the
        # band, and >= 100 average AOI neighbors is the density clause.
        if tier0_share < 0.25:
            raise ScenarioInvariantError(
                f"hotspot endgame tier0_share {tier0_share} < 0.25 — the "
                "crowd is not dense enough to be a hotspot")
        if avg_neighbors < 100.0:
            raise ScenarioInvariantError(
                f"hotspot endgame avg AOI neighbors {avg_neighbors} < 100 "
                "— not worst-case density")
        inv.update({
            "max_cell_density": int(counts.max()),
            "final_aoi_pairs": in_aoi,
            "avg_aoi_neighbors": avg_neighbors,
            "tier0_share": tier0_share,
        })
        return inv


# FIXED config. n=1024 over a 48x48 grid: the final 200-radius crowd
# peaks ~90/cell (under cell_capacity 128, dropped stays 0) at ~200
# average AOI neighbors each, and lands in ~6 grid columns — far beyond
# one strip's 128-row budget on the 8-shard mesh, guaranteeing
# strip_overflow fallbacks — the 1280-row capacity leaves only 25% slot
# slack (160-row strips), so the pre-crowd uniform world shards natively
# while the crowd provably cannot. Geometry satisfies the sharded
# constraints: 1280 % 64 == 0, 32768 % 8 == 0, 48 >= 4 * 8.
SPEC = register(ScenarioSpec(
    name="hotspot",
    description=("everyone converges on one crowd disc: worst-case AOI "
                 "density, tier-0-everything sync, sharded "
                 "strip_overflow fallback required"),
    config={
        "n": 1024, "capacity": 1280, "cell_size": 100.0, "grid": 48,
        "space_slots": 1, "cell_capacity": 128, "max_events": 32768,
        "shards": 8, "ticks": 56, "radius": 100.0, "repeats": 3,
        "seed": 16,
    },
    factory=HotspotWorld,
))

"""service_heavy: chat/mail/ranking traffic through the service layer's
shard routing with storage pressure against the circuit breaker.

Movement is mild (the AOI tier idles at a realistic baseline) — the load
lives OFF the grid: every tick issues a fixed batch of service ops, each
routed by the REAL ``service.shard_by_key`` to a per-shard receipt
counter (chat 4 shards / mail 2 / ranking 2 — the reference's fourth
scaling axis) and persisted through the REAL storage worker thread
(``storage.save``).  Mid-run, an injected backend outage fails enough
consecutive writes to trip the circuit breaker in ``storage/circuit.py``
— the breaker MUST be observed OPEN, saves defer instead of dropping,
and after the heal the breaker must close and the deferred queue drain
to zero with every document's final value intact (``lost_saves == 0``).

Invariants: exactly-once per-shard receipts (the routing trajectory is
seed-deterministic), ``circuit_opened`` true, ``lost_saves`` 0, op
totals, plus the shared event clauses.  The save p95 is wall-clock and
rides the headline OUTSIDE invariants.
"""

from __future__ import annotations

import shutil
import tempfile
import time
from typing import Any, Dict, List, Mapping, Optional

import numpy as np

from goworld_tpu.scenarios import (
    ScenarioInvariantError,
    ScenarioSpec,
    ScenarioWorld,
    register,
)

_KINDS = ("chat", "mail", "ranking")


class _OutageBackend:
    """Storage-backend wrapper failing the next ``fail_writes`` writes —
    the scenario-local cousin of the chaos harness's FlakyBackend (kept
    local so importing the scenarios package never drags in the cluster
    stack)."""

    def __init__(self, inner: Any) -> None:
        self.inner = inner
        self.fail_writes = 0
        self.writes = 0
        self.failed = 0

    def write(self, typename: str, eid: str, data: Any) -> None:
        if self.fail_writes > 0:
            self.fail_writes -= 1
            self.failed += 1
            raise IOError("scenario-injected storage outage")
        self.writes += 1
        self.inner.write(typename, eid, data)

    def read(self, typename: str, eid: str) -> Any:
        return self.inner.read(typename, eid)

    def exists(self, typename: str, eid: str) -> bool:
        return self.inner.exists(typename, eid)

    def list_entity_ids(self, typename: str) -> Any:
        return self.inner.list_entity_ids(typename)


class ServiceHeavyWorld(ScenarioWorld):
    def __init__(self, config: Mapping[str, Any], seed: int) -> None:
        super().__init__(config, seed)
        self.pos = self.rng.uniform(
            0.0, self.world, (self.cap, 2)).astype(np.float32)
        self.ops_per_tick = int(config.get("ops_per_tick", 64))
        self.kind_shards = {
            "chat": int(config.get("chat_shards", 4)),
            "mail": int(config.get("mail_shards", 2)),
            "ranking": int(config.get("ranking_shards", 2)),
        }
        self.receipts: Dict[str, List[int]] = {
            k: [0] * s for k, s in self.kind_shards.items()}
        self.ops_total = 0
        self.expected: Dict[str, Dict[str, Any]] = {}
        self.circuit_opened = False
        self.lost_saves = -1  # set by check_engine after the drain
        self.op_ms: List[float] = []
        self._heartbeat: Dict[str, Any] = {}
        self._tmpdir: Optional[str] = None
        self._outage: Optional[_OutageBackend] = None

    # --- storage lifecycle --------------------------------------------------

    def setup(self) -> None:
        from goworld_tpu import storage
        from goworld_tpu.config.read_config import StorageConfig

        self._tmpdir = tempfile.mkdtemp(prefix="gw_scenario_es_")
        # initialize() is the one public way to set the retry/circuit
        # knobs; set_backend() then swaps in the outage wrapper while
        # KEEPING those knobs (storage/__init__.py contract).
        storage.initialize(StorageConfig(
            type="filesystem", directory=self._tmpdir,
            retry_base_interval=0.02, retry_max_interval=0.1,
            circuit_failure_threshold=3, circuit_cooldown=0.25,
        ))
        self._outage = _OutageBackend(storage.get_backend())
        storage.set_backend(self._outage)

    def teardown(self) -> None:
        from goworld_tpu import storage
        from goworld_tpu.config.read_config import StorageConfig
        from goworld_tpu.storage.circuit import CircuitBreaker

        try:
            # Best-effort drain so measure passes (which inject the
            # outage but skip check_engine's recovery) don't discard a
            # deferred queue at the backend swap below.
            deadline = time.monotonic() + 5.0
            while ((storage.deferred_count() > 0
                    or storage.circuit_state() != CircuitBreaker.CLOSED)
                   and time.monotonic() < deadline):
                storage.save("ScenarioDoc", "heartbeat", self._heartbeat)
                time.sleep(0.05)
            storage.wait_clear(10.0)
        finally:
            # Restore default knobs for whoever initializes next, then
            # drop the backend entirely (test-suite hygiene).
            storage.initialize(StorageConfig(
                type="filesystem", directory=self._tmpdir or "."))
            storage.set_backend(None)
            if self._tmpdir:
                shutil.rmtree(self._tmpdir, ignore_errors=True)
            self._tmpdir = None
            self._outage = None

    # --- per-tick drive -----------------------------------------------------

    def tick(self, t: int) -> bool:
        # Mild drift (vectorized; gwlint R2 hot path) — the real load is
        # the service/storage batch, issued from the non-hot helper.
        # Rebind, don't mutate: the previous buffer may back an in-flight
        # pipelined dispatch.
        self.pos = np.clip(
            self.pos + self.rng.normal(
                0.0, 2.0, (self.cap, 2)).astype(np.float32),
            0.0, self.world)
        self._issue_ops(t)
        return False

    def _issue_ops(self, t: int) -> None:
        from goworld_tpu import service, storage

        if self._outage is not None and t == int(self.config["ticks"]) // 3:
            # Outage: one more consecutive failure than the breaker
            # threshold, so the half-open probe fails once too.
            self._outage.fail_writes = (
                int(self.config.get("fail_burst", 4)))
        users = self.rng.integers(0, 4096, self.ops_per_tick)
        t0 = time.perf_counter()
        for i, u in enumerate(users.tolist()):
            kind = _KINDS[(t + i) % len(_KINDS)]
            shard = service.shard_by_key(f"user{u}", self.kind_shards[kind])
            self.receipts[kind][shard] += 1
            doc = f"{kind}-{shard}-{u % 8}"
            payload = {"tick": t, "user": int(u), "seq": self.ops_total}
            self.expected[doc] = payload
            storage.save("ScenarioDoc", doc, payload)
            self.ops_total += 1
        self.op_ms.append(
            (time.perf_counter() - t0) * 1000.0 / max(self.ops_per_tick, 1))
        if self._outage is not None and not self.circuit_opened:
            from goworld_tpu.storage.circuit import CircuitBreaker

            if storage.circuit_state() != CircuitBreaker.CLOSED:
                self.circuit_opened = True

    # --- end-of-run clauses -------------------------------------------------

    def check_engine(self, eng: Any, engine: str) -> None:
        from goworld_tpu import storage
        from goworld_tpu.storage.circuit import CircuitBreaker

        if not self.circuit_opened:
            raise ScenarioInvariantError(
                "the injected outage never opened the circuit breaker")
        # Recovery: keep nudging the worker (each save triggers a
        # deferred flush attempt) until the breaker closes and the
        # deferred queue drains — bounded wait, then hard fail.  The
        # heartbeat doc is NOT counted in ops_total/docs invariants (its
        # save count is wall-clock-dependent).
        deadline = time.monotonic() + 15.0
        hb = 0
        while (storage.deferred_count() > 0
               or storage.circuit_state() != CircuitBreaker.CLOSED):
            if time.monotonic() > deadline:
                raise ScenarioInvariantError(
                    f"storage never recovered: deferred="
                    f"{storage.deferred_count()} "
                    f"circuit={storage.circuit_state()}")
            hb += 1
            self._heartbeat = {"tick": -1, "user": -1, "seq": hb}
            storage.save("ScenarioDoc", "heartbeat", self._heartbeat)
            time.sleep(0.05)
        if not storage.wait_clear(10.0):
            raise ScenarioInvariantError("storage queue failed to drain")
        assert self._outage is not None
        lost = 0
        for doc, payload in self.expected.items():
            if self._outage.inner.read("ScenarioDoc", doc) != payload:
                lost += 1
        self.lost_saves = lost
        if lost:
            raise ScenarioInvariantError(
                f"{lost}/{len(self.expected)} documents lost or stale "
                "after circuit recovery — deferred writes were dropped")

    def extra_headline(self) -> Dict[str, Any]:
        ms = sorted(self.op_ms)
        p95 = ms[int(0.95 * (len(ms) - 1))] if ms else 0.0
        return {"service_op_p95_ms": round(p95, 4),
                "storage_writes": self._outage.writes if self._outage else 0}

    def invariants(self) -> Dict[str, Any]:
        inv = super().invariants()
        inv.update({
            "receipts": {k: list(v) for k, v in self.receipts.items()},
            "ops_total": self.ops_total,
            "circuit_opened": self.circuit_opened,
            "lost_saves": self.lost_saves,
            "docs": len(self.expected),
        })
        return inv


# FIXED config. Small n (the load is service-side); geometry still
# satisfies the sharded engine on the 8-device mesh (512 % 64 == 0,
# 32768 % 8 == 0, 32 >= 4 * 8).
SPEC = register(ScenarioSpec(
    name="service_heavy",
    description=("chat/mail/ranking shard routing + storage saves with a "
                 "mid-run outage through the circuit breaker; "
                 "exactly-once receipts, zero lost saves"),
    config={
        "n": 512, "capacity": 1024, "cell_size": 100.0, "grid": 32,
        "space_slots": 1, "cell_capacity": 64, "max_events": 32768,
        "shards": 8, "ticks": 48, "radius": 100.0, "repeats": 2,
        "seed": 16,
    },
    factory=ServiceHeavyWorld,
))

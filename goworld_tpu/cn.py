# -*- coding: utf-8 -*-
"""goworld_tpu 中文文档入口（对应参考实现的 ``cn/goworld_cn.go``：仅文档与
门面转发，无独立逻辑）。

goworld_tpu 是一个分布式游戏服务器引擎，理论上支持无限横向扩展，并将
AOI（兴趣范围）热点路径整体搬到 TPU 上批量计算。

一个部署由三种进程组成：dispatcher、gate、game。

- gate 负责接受客户端连接（TCP、可靠 UDP、WebSocket，支持 TLS 与压缩），
  并维护按属性过滤广播的 filter 树。
- dispatcher 是 game 与 gate 之间的数据转发中心：维护 entity 路由表，
  在实体迁移、进程冻结期间缓存数据包，并做新建实体的负载均衡。
- game 承载全部游戏逻辑，单线程事件驱动（asyncio 主循环），逻辑代码无需
  考虑并发与加锁；任何逻辑都不应调用阻塞的系统调用。

逻辑模型与参考实现一致：场景（Space）与实体（Entity）。客户端登录后在
某个 game 上创建 Account（boot entity），登录成功后创建 Player 并通过
give_client_to 移交客户端。实体可通过 enter_space 在 game 之间无缝迁移
（属性、定时器、客户端绑定全部打包重建）；space 常驻创建它的 game。

与参考实现不同的是 AOI 平面：每个 game 的所有 space 每 tick 合并为一次
JAX/Pallas 核函数调用（ops/neighbor.py），多芯片时实体槽位分片并通过
ICI all-gather 全局查询（parallel/mesh.py，配置 ``[aoi] mesh_shards``）。

运维命令（参考 cmd/goworld）::

    python -m goworld_tpu.cli start examples.test_game   # 启动部署
    python -m goworld_tpu.cli reload examples.test_game  # 热更新（冻结/恢复）
    python -m goworld_tpu.cli stop examples.test_game    # 停止
    python -m goworld_tpu.client -N 200 -strict          # 压测机器人

本模块将全部公共 API 从 :mod:`goworld_tpu.facade` 原样转发。
"""

from goworld_tpu.facade import *  # noqa: F401,F403
from goworld_tpu.facade import __all__  # noqa: F401

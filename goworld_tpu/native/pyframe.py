"""Pure-Python reference implementation of the _fastframe surface.

Semantics must match fastframe.c exactly — the parity fuzz suite in
tests/test_native.py drives both over the same corpus. This is also the
fallback when the C module can't build (GWT_NO_NATIVE=1, no compiler).
"""

from __future__ import annotations

import struct
import zlib

_LEN = struct.Struct("<I")
_COMPRESSED_BIT = 0x80000000
_LEN_MASK = 0x7FFFFFFF


def split(data, max_packet: int):
    """Parse complete frames out of ``data``.

    Returns (frames, consumed, error) where frames =
    [(msgtype, payload_bytes)] and error is None or a str describing the
    malformed frame parsing STOPPED at (bad length, bad zlib stream,
    bounded-inflate overflow). Frames parsed before the malformed one are
    still returned — callers deliver them, then treat error as a
    connection-fatal condition.
    """
    buf = bytes(data)
    frames = []
    off = 0
    n = len(buf)
    while n - off >= 4:
        (raw,) = _LEN.unpack_from(buf, off)
        compressed = bool(raw & _COMPRESSED_BIT)
        body_len = raw & _LEN_MASK
        if body_len < 2 or body_len > max_packet:
            return frames, off, f"bad packet length {body_len}"
        if n - off - 4 < body_len:
            break  # incomplete frame
        body = buf[off + 4 : off + 4 + body_len]
        if compressed:
            try:
                d = zlib.decompressobj()
                body = d.decompress(body, max_packet)
                if d.unconsumed_tail or not d.eof:
                    return frames, off, "compressed packet exceeds size cap"
            except zlib.error as exc:
                return frames, off, f"bad compressed packet: {exc}"
            if len(body) < 2:
                return frames, off, "bad decompressed length"
        msgtype = body[0] | (body[1] << 8)
        frames.append((msgtype, body[2:]))
        off += 4 + body_len
    return frames, off, None


def pack(msgtype: int, payload, compress: bool, threshold: int,
         max_packet: int) -> bytes:
    """Build one framed buffer (optionally zlib level 1 when it shrinks)."""
    if not 0 <= msgtype <= 0xFFFF:
        raise ValueError(f"msgtype {msgtype} out of u16 range")
    payload = bytes(payload)
    body = struct.pack("<H", msgtype) + payload
    if len(body) > max_packet:
        raise ValueError(f"packet too large: {len(body)}")
    flag = 0
    if compress and len(body) >= threshold:
        deflated = zlib.compress(body, 1)
        if len(deflated) < len(body):
            body = deflated
            flag = _COMPRESSED_BIT
    return _LEN.pack(len(body) | flag) + body

"""Pure-Python reference implementation of the _fastframe surface.

Semantics must match fastframe.c exactly — the parity fuzz suite in
tests/test_native.py drives both over the same corpus. This is also the
fallback when the C module can't build (GWT_NO_NATIVE=1, no compiler).

Compression formats (``compress`` arg of :func:`pack`): 0/False = off,
1 = zlib (deflate level 1), 2 = snappy — the reference's actual gate↔client
codec (ClientProxy.go:42-45 wraps conns in snappy streams). The snappy
block-format codec here is from scratch (the library isn't in the image):
format per the public Snappy format description — varint uncompressed-length
preamble, then literal/copy elements (tag low 2 bits: 00 literal, 01 copy
with 11-bit offset, 10 copy with 2-byte offset, 11 copy with 4-byte
offset). The receive side auto-detects per packet via two length-prefix
flag bits, so enabling either format stays one-sided safe.
"""

from __future__ import annotations

import struct
import zlib

_LEN = struct.Struct("<I")
_ZLIB_BIT = 0x80000000
_SNAPPY_BIT = 0x40000000
_LEN_MASK = 0x3FFFFFFF

COMPRESS_OFF = 0
COMPRESS_ZLIB = 1
COMPRESS_SNAPPY = 2

_SNAPPY_BLOCK = 32768  # fragment size: every offset fits a 2-byte copy


# --- snappy block codec ------------------------------------------------------


def _snappy_emit_literal(out: bytearray, data: bytes, s: int, e: int) -> None:
    length = e - s
    if length <= 0:
        return
    n1 = length - 1
    if n1 < 60:
        out.append(n1 << 2)
    elif n1 < 0x100:
        out.append(60 << 2)
        out.append(n1)
    else:  # length <= 32768+preamble slack: two bytes always suffice
        out.append(61 << 2)
        out.append(n1 & 0xFF)
        out.append((n1 >> 8) & 0xFF)
    out += data[s:e]


def _snappy_emit_copy(out: bytearray, offset: int, length: int) -> None:
    # Long matches: 64-byte chunks, leaving a >=4 remainder (emitting 60
    # instead of 64 when the tail would drop under 4 — copies can't encode
    # lengths 1..3).
    while length >= 68:
        out.append((63 << 2) | 2)
        out.append(offset & 0xFF)
        out.append((offset >> 8) & 0xFF)
        length -= 64
    if length > 64:
        out.append((59 << 2) | 2)
        out.append(offset & 0xFF)
        out.append((offset >> 8) & 0xFF)
        length -= 60
    if length <= 11 and offset < 2048:
        out.append(1 | ((length - 4) << 2) | ((offset >> 8) << 5))
        out.append(offset & 0xFF)
    else:
        out.append(((length - 1) << 2) | 2)
        out.append(offset & 0xFF)
        out.append((offset >> 8) & 0xFF)


def snappy_compress(data: bytes) -> bytes:
    """Snappy block-format compress (greedy 4-byte-hash matcher, 32 KiB
    fragments like the standard encoder so offsets fit 2 bytes)."""
    out = bytearray()
    n = len(data)
    v = n
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    i = 0
    while i < n:
        base = i
        block_end = min(i + _SNAPPY_BLOCK, n)
        table: dict[bytes, int] = {}
        lit_start = i
        while i < block_end:
            if block_end - i < 4:
                i = block_end
                break
            key = data[i:i + 4]
            cand = table.get(key, -1)
            table[key] = i
            if cand >= base:
                _snappy_emit_literal(out, data, lit_start, i)
                m, c = i + 4, cand + 4
                while m < block_end and data[m] == data[c]:
                    m += 1
                    c += 1
                _snappy_emit_copy(out, i - cand, m - i)
                i = m
                lit_start = i
            else:
                i += 1
        _snappy_emit_literal(out, data, lit_start, block_end)
    return bytes(out)


def snappy_decompress(data: bytes, cap: int) -> bytes:
    """Decode a snappy block; raises ValueError on malformed input or when
    the declared/produced size exceeds ``cap`` (decompression-bomb guard,
    same contract as the bounded zlib inflate)."""
    n = len(data)
    ulen = 0
    shift = 0
    i = 0
    while True:
        if i >= n or shift > 31:
            raise ValueError("bad snappy preamble")
        b = data[i]
        i += 1
        ulen |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    if ulen > cap:
        raise ValueError("compressed packet exceeds size cap")
    out = bytearray()
    while i < n:
        t = data[i]
        i += 1
        typ = t & 3
        if typ == 0:  # literal
            ln = t >> 2
            if ln >= 60:
                nb = ln - 59
                if i + nb > n:
                    raise ValueError("bad snappy stream")
                ln = int.from_bytes(data[i:i + nb], "little")
                i += nb
            ln += 1
            if i + ln > n or len(out) + ln > ulen:
                raise ValueError("bad snappy stream")
            out += data[i:i + ln]
            i += ln
            continue
        if typ == 1:
            if i >= n:
                raise ValueError("bad snappy stream")
            ln = ((t >> 2) & 7) + 4
            off = ((t >> 5) << 8) | data[i]
            i += 1
        elif typ == 2:
            if i + 2 > n:
                raise ValueError("bad snappy stream")
            ln = (t >> 2) + 1
            off = data[i] | (data[i + 1] << 8)
            i += 2
        else:
            if i + 4 > n:
                raise ValueError("bad snappy stream")
            ln = (t >> 2) + 1
            off = int.from_bytes(data[i:i + 4], "little")
            i += 4
        pos = len(out)
        if off == 0 or off > pos or pos + ln > ulen:
            raise ValueError("bad snappy stream")
        if off >= ln:
            start = pos - off
            out += out[start:start + ln]
        else:  # overlapping copy replicates the tail pattern bytewise
            for _ in range(ln):
                out.append(out[-off])
    if len(out) != ulen:
        raise ValueError("bad snappy stream")
    return bytes(out)


# --- framing -----------------------------------------------------------------


def split(data, max_packet: int):
    """Parse complete frames out of ``data``.

    Returns (frames, consumed, error) where frames =
    [(msgtype, payload_bytes)] and error is None or a str describing the
    malformed frame parsing STOPPED at (bad length, bad compressed stream,
    bounded-decompress overflow). Frames parsed before the malformed one
    are still returned — callers deliver them, then treat error as a
    connection-fatal condition.
    """
    buf = bytes(data)
    frames = []
    off = 0
    n = len(buf)
    while n - off >= 4:
        (raw,) = _LEN.unpack_from(buf, off)
        is_zlib = bool(raw & _ZLIB_BIT)
        is_snappy = bool(raw & _SNAPPY_BIT)
        body_len = raw & _LEN_MASK
        if is_zlib and is_snappy:
            return frames, off, "bad packet flags"
        if body_len < 2 or body_len > max_packet:
            return frames, off, f"bad packet length {body_len}"
        if n - off - 4 < body_len:
            break  # incomplete frame
        body = buf[off + 4 : off + 4 + body_len]
        if is_zlib:
            try:
                d = zlib.decompressobj()
                body = d.decompress(body, max_packet)
                if d.unconsumed_tail or not d.eof:
                    return frames, off, "compressed packet exceeds size cap"
            except zlib.error as exc:
                return frames, off, f"bad compressed packet: {exc}"
            if len(body) < 2:
                return frames, off, "bad decompressed length"
        elif is_snappy:
            try:
                body = snappy_decompress(body, max_packet)
            except ValueError as exc:
                return frames, off, str(exc)
            if len(body) < 2:
                return frames, off, "bad decompressed length"
        msgtype = body[0] | (body[1] << 8)
        frames.append((msgtype, body[2:]))
        off += 4 + body_len
    return frames, off, None


def pack(msgtype: int, payload, compress, threshold: int,
         max_packet: int) -> bytes:
    """Build one framed buffer.

    ``compress``: 0/False off, 1/True zlib (level 1), 2 snappy — the body
    is compressed when it reaches ``threshold`` AND the codec actually
    shrinks it (the flag bit tells the receiver which codec, per packet).
    """
    if not 0 <= msgtype <= 0xFFFF:
        raise ValueError(f"msgtype {msgtype} out of u16 range")
    payload = bytes(payload)
    body = struct.pack("<H", msgtype) + payload
    if len(body) > max_packet:
        raise ValueError(f"packet too large: {len(body)}")
    flag = 0
    mode = int(compress)
    if mode and len(body) >= threshold:
        if mode == COMPRESS_SNAPPY:
            packed = snappy_compress(body)
            if len(packed) < len(body):
                body = packed
                flag = _SNAPPY_BIT
        else:
            deflated = zlib.compress(body, 1)
            if len(deflated) < len(body):
                body = deflated
                flag = _ZLIB_BIT
    return _LEN.pack(len(body) | flag) + body

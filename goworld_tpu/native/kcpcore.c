/* _kcpcore — the KCP control block in C (the transport's per-datagram
 * hot loop; the reference runs kcp-go compiled, and the pure-Python
 * control block walls a single-core bot fleet at ~10 MB/s/session
 * during restore bursts — BENCH_NOTES round 5).
 *
 * Semantics mirror netutil/kcp.py's class KCP EXACTLY — that Python
 * implementation is the pinned reference (wire vectors in
 * tests/test_kcp.py); the parity suite drives both over random
 * lossy transfers and asserts identical delivered streams. Segment
 * layout and protocol constants per the public KCP spec:
 *   [u32 conv][u8 cmd][u8 frg][u16 wnd][u32 ts][u32 sn][u32 una]
 *   [u32 len] + data, little-endian; cmds 81..84.
 *
 * Exposed type: KCPCore(conv, output_callable)
 *   .send(bytes) -> int         .recv() -> bytes | None
 *   .input(bytes) -> int        .update(ms) / .check(ms) -> ms
 *   .flush()                    .set_nodelay(nd, interval, resend, nc)
 *   .set_wndsize(snd, rcv)      .set_mtu(mtu)
 *   .waiting_send() -> int      .idle() -> bool
 *   attrs: conv, state, stream (rw), updated, current (rw), mss,
 *          interval, rmt_wnd, rx_rto, snd_una, snd_nxt, rcv_nxt,
 *          probe_wait, has_acks, snd_buf_len, snd_queue_len
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

#define K_RTO_NDL 30
#define K_RTO_MIN 100
#define K_RTO_DEF 200
#define K_RTO_MAX 60000
#define K_CMD_PUSH 81
#define K_CMD_ACK 82
#define K_CMD_WASK 83
#define K_CMD_WINS 84
#define K_ASK_SEND 1
#define K_ASK_TELL 2
#define K_WND_SND 32
#define K_WND_RCV 128
#define K_MTU_DEF 1400
#define K_INTERVAL 100
#define K_OVERHEAD 24
#define K_DEADLINK 20
#define K_THRESH_INIT 2
#define K_THRESH_MIN 2
#define K_PROBE_INIT 7000
#define K_PROBE_LIMIT 120000

static int32_t itimediff(uint32_t later, uint32_t earlier) {
    return (int32_t)(later - earlier);
}

typedef struct kseg {
    struct kseg *next;
    uint32_t frg, wnd, ts, sn, una;
    uint32_t resendts, rto, fastack, xmit;
    Py_ssize_t len, cap; /* cap > len on stream-mode tails: coalesce is an
                            in-place memcpy, never a realloc+relink */
    unsigned char data[];
} kseg;

typedef struct {
    kseg *head, *tail;
    Py_ssize_t n;
} klist;

static void klist_push(klist *l, kseg *s) {
    s->next = NULL;
    if (l->tail) l->tail->next = s;
    else l->head = s;
    l->tail = s;
    l->n++;
}

static kseg *klist_pop(klist *l) {
    kseg *s = l->head;
    if (s == NULL) return NULL;
    l->head = s->next;
    if (l->head == NULL) l->tail = NULL;
    l->n--;
    return s;
}

static void klist_clear(klist *l) {
    kseg *s;
    while ((s = klist_pop(l)) != NULL) PyMem_Free(s);
}

static kseg *kseg_new(const unsigned char *data, Py_ssize_t len,
                      Py_ssize_t cap) {
    if (cap < len) cap = len;
    kseg *s = (kseg *)PyMem_Malloc(sizeof(kseg) + (size_t)cap);
    if (s == NULL) return NULL;
    memset(s, 0, sizeof(kseg));
    s->len = len;
    s->cap = cap;
    if (len) memcpy(s->data, data, (size_t)len);
    return s;
}

typedef struct {
    PyObject_HEAD
    PyObject *output; /* callable(bytes) */
    uint32_t conv, snd_una, snd_nxt, rcv_nxt;
    uint32_t ssthresh;
    int32_t rx_rttval, rx_srtt;
    uint32_t rx_rto, rx_minrto;
    uint32_t snd_wnd, rcv_wnd, rmt_wnd, cwnd, probe;
    uint32_t mtu, mss;
    int stream;
    uint32_t interval_, ts_flush;
    int nodelay_, updated;
    uint32_t ts_probe, probe_wait;
    uint32_t dead_link, incr;
    int state;
    uint32_t current;
    int nocwnd, fastresend;
    klist snd_queue, rcv_queue, snd_buf, rcv_buf; /* rcv_buf sn-sorted */
    uint32_t *acklist; /* pairs (sn, ts) */
    Py_ssize_t ackcount, ackcap;
    uint32_t xmit;
    unsigned char *obuf; /* datagram assembly buffer (grow-only) */
    size_t obuf_cap;
    Py_ssize_t olen;
} KCPCore;

/* --- output assembly ----------------------------------------------------- */

static void wr_u32(unsigned char *p, uint32_t v) {
    p[0] = v & 0xff; p[1] = (v >> 8) & 0xff;
    p[2] = (v >> 16) & 0xff; p[3] = (v >> 24) & 0xff;
}

static void wr_u16(unsigned char *p, uint32_t v) {
    p[0] = v & 0xff; p[1] = (v >> 8) & 0xff;
}

static uint32_t rd_u32(const unsigned char *p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
           ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
}

static uint32_t rd_u16(const unsigned char *p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8);
}

static int kcp_outflush(KCPCore *k) {
    if (k->olen == 0) return 0;
    if (k->output == NULL) { /* cleared by the gc mid-collection */
        k->olen = 0;
        return 0;
    }
    PyObject *b = PyBytes_FromStringAndSize((const char *)k->obuf, k->olen);
    k->olen = 0;
    if (b == NULL) return -1;
    PyObject *r = PyObject_CallOneArg(k->output, b);
    Py_DECREF(b);
    if (r == NULL) return -1;
    Py_DECREF(r);
    return 0;
}

/* Append one encoded segment header (+payload) to the datagram buffer,
 * flushing first if it would overflow the mtu. */
static int kcp_emit(KCPCore *k, uint32_t cmd, uint32_t frg, uint32_t wnd,
                    uint32_t ts, uint32_t sn, uint32_t una,
                    const unsigned char *data, Py_ssize_t len) {
    if (k->olen + K_OVERHEAD + len > (Py_ssize_t)k->mtu && k->olen > 0) {
        if (kcp_outflush(k) != 0) return -1;
    }
    unsigned char *w = k->obuf + k->olen;
    wr_u32(w, k->conv);
    w[4] = (unsigned char)cmd;
    w[5] = (unsigned char)frg;
    wr_u16(w + 6, wnd);
    wr_u32(w + 8, ts);
    wr_u32(w + 12, sn);
    wr_u32(w + 16, una);
    wr_u32(w + 20, (uint32_t)len);
    if (len) memcpy(w + K_OVERHEAD, data, (size_t)len);
    k->olen += K_OVERHEAD + len;
    return 0;
}

/* --- core helpers (mirror kcp.py exactly) -------------------------------- */

static uint32_t wnd_unused(KCPCore *k) {
    if ((Py_ssize_t)k->rcv_wnd > k->rcv_queue.n)
        return k->rcv_wnd - (uint32_t)k->rcv_queue.n;
    return 0;
}

static void update_ack(KCPCore *k, int32_t rtt) {
    if (k->rx_srtt == 0) {
        k->rx_srtt = rtt;
        k->rx_rttval = rtt / 2;
    } else {
        int32_t delta = rtt - k->rx_srtt;
        if (delta < 0) delta = -delta;
        k->rx_rttval = (3 * k->rx_rttval + delta) / 4;
        k->rx_srtt = (7 * k->rx_srtt + rtt) / 8;
        if (k->rx_srtt < 1) k->rx_srtt = 1;
    }
    uint32_t rto = (uint32_t)k->rx_srtt +
        (k->interval_ > (uint32_t)(4 * k->rx_rttval)
             ? k->interval_ : (uint32_t)(4 * k->rx_rttval));
    if (rto < k->rx_minrto) rto = k->rx_minrto;
    if (rto > K_RTO_MAX) rto = K_RTO_MAX;
    k->rx_rto = rto;
}

static void shrink_buf(KCPCore *k) {
    k->snd_una = k->snd_buf.head ? k->snd_buf.head->sn : k->snd_nxt;
}

static void parse_ack(KCPCore *k, uint32_t sn) {
    if (itimediff(sn, k->snd_una) < 0 || itimediff(sn, k->snd_nxt) >= 0)
        return;
    kseg **pp = &k->snd_buf.head;
    kseg *prev = NULL;
    for (kseg *s = k->snd_buf.head; s; prev = s, s = s->next) {
        if (s->sn == sn) {
            *pp = s->next;
            if (k->snd_buf.tail == s) k->snd_buf.tail = prev;
            k->snd_buf.n--;
            PyMem_Free(s);
            return;
        }
        if (itimediff(sn, s->sn) < 0) return;
        pp = &s->next;
    }
}

static void parse_una(KCPCore *k, uint32_t una) {
    while (k->snd_buf.head && itimediff(k->snd_buf.head->sn, una) < 0) {
        kseg *s = klist_pop(&k->snd_buf);
        PyMem_Free(s);
    }
}

static void parse_fastack(KCPCore *k, uint32_t sn) {
    if (itimediff(sn, k->snd_una) < 0 || itimediff(sn, k->snd_nxt) >= 0)
        return;
    for (kseg *s = k->snd_buf.head; s; s = s->next) {
        if (itimediff(sn, s->sn) < 0) break;
        if (sn != s->sn) s->fastack++;
    }
}

static void move_rcv_buf(KCPCore *k) {
    while (k->rcv_buf.head && k->rcv_buf.head->sn == k->rcv_nxt &&
           k->rcv_queue.n < (Py_ssize_t)k->rcv_wnd) {
        kseg *s = klist_pop(&k->rcv_buf);
        klist_push(&k->rcv_queue, s);
        k->rcv_nxt++;
    }
}

static void parse_data(KCPCore *k, uint32_t sn, uint32_t frg,
                       const unsigned char *data, Py_ssize_t len) {
    if (itimediff(sn, k->rcv_nxt + k->rcv_wnd) >= 0 ||
        itimediff(sn, k->rcv_nxt) < 0)
        return;
    /* ordered insert (dedup) — bursts arrive in order, so scan from the
     * tail via a prev-pointer walk (list is short: <= rcv_wnd) */
    kseg **pp = &k->rcv_buf.head;
    kseg *ins_after = NULL;
    for (kseg *s = k->rcv_buf.head; s; s = s->next) {
        if (s->sn == sn) return; /* duplicate */
        if (itimediff(sn, s->sn) < 0) break;
        ins_after = s;
        pp = &s->next;
    }
    kseg *ns = kseg_new(data, len, len);
    if (ns == NULL) return; /* OOM: drop (ARQ retransmits) */
    ns->sn = sn;
    ns->frg = frg;
    ns->next = *pp;
    *pp = ns;
    if (ins_after == k->rcv_buf.tail) k->rcv_buf.tail = ns;
    k->rcv_buf.n++;
    move_rcv_buf(k);
}

static int ack_push(KCPCore *k, uint32_t sn, uint32_t ts) {
    if (k->ackcount + 1 > k->ackcap) {
        Py_ssize_t ncap = k->ackcap ? k->ackcap * 2 : 16;
        uint32_t *na = (uint32_t *)PyMem_Realloc(
            k->acklist, (size_t)ncap * 2 * sizeof(uint32_t));
        if (na == NULL) return -1;
        k->acklist = na;
        k->ackcap = ncap;
    }
    k->acklist[k->ackcount * 2] = sn;
    k->acklist[k->ackcount * 2 + 1] = ts;
    k->ackcount++;
    return 0;
}

static Py_ssize_t peeksize(KCPCore *k) {
    kseg *s = k->rcv_queue.head;
    if (s == NULL) return -1;
    if (s->frg == 0) return s->len;
    if (k->rcv_queue.n < (Py_ssize_t)s->frg + 1) return -1;
    Py_ssize_t length = 0;
    for (; s; s = s->next) {
        length += s->len;
        if (s->frg == 0) break;
    }
    return length;
}

/* --- methods ------------------------------------------------------------- */

static PyObject *K_send(KCPCore *k, PyObject *arg) {
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) != 0) return NULL;
    const unsigned char *buf = (const unsigned char *)view.buf;
    Py_ssize_t len = view.len;
    if (len == 0 && !k->stream) {
        PyBuffer_Release(&view);
        return PyLong_FromLong(-1);
    }
    if (k->stream && k->snd_queue.tail) {
        kseg *tail = k->snd_queue.tail;
        /* Stream-mode tails are allocated with mss capacity, so the
         * coalesce is an O(1) in-place memcpy (a realloc here would need
         * an O(n) predecessor relink when the block moves — quadratic
         * under small-send bursts, code-review r5). Capacity is bounded
         * by the coalesce target itself: min(cap, mss). */
        Py_ssize_t limit = tail->cap < (Py_ssize_t)k->mss
                               ? tail->cap : (Py_ssize_t)k->mss;
        if (tail->len < limit) {
            Py_ssize_t take = limit - tail->len;
            if (take > len) take = len;
            memcpy(tail->data + tail->len, buf, (size_t)take);
            tail->len += take;
            tail->frg = 0;
            buf += take;
            len -= take;
        }
    }
    if (len == 0) {
        PyBuffer_Release(&view);
        return PyLong_FromLong(0);
    }
    Py_ssize_t count = (len + k->mss - 1) / (Py_ssize_t)k->mss;
    if (count == 0) count = 1;
    if (count >= K_WND_RCV) {
        PyBuffer_Release(&view);
        return PyLong_FromLong(-2);
    }
    for (Py_ssize_t i = 0; i < count; i++) {
        Py_ssize_t off = i * (Py_ssize_t)k->mss;
        Py_ssize_t n = len - off < (Py_ssize_t)k->mss
                           ? len - off : (Py_ssize_t)k->mss;
        /* In stream mode the LAST fragment becomes the coalescible tail:
         * give it full mss capacity up front (O(1) later coalesce). */
        Py_ssize_t cap =
            (k->stream && i == count - 1) ? (Py_ssize_t)k->mss : n;
        kseg *s = kseg_new(buf + off, n, cap);
        if (s == NULL) {
            PyBuffer_Release(&view);
            return PyErr_NoMemory();
        }
        s->frg = k->stream ? 0 : (uint32_t)(count - i - 1);
        klist_push(&k->snd_queue, s);
    }
    PyBuffer_Release(&view);
    return PyLong_FromLong(0);
}

static PyObject *K_recv(KCPCore *k, PyObject *noarg) {
    Py_ssize_t size = peeksize(k);
    if (size < 0) Py_RETURN_NONE;
    int recover = k->rcv_queue.n >= (Py_ssize_t)k->rcv_wnd;
    PyObject *out = PyBytes_FromStringAndSize(NULL, size);
    if (out == NULL) return NULL;
    unsigned char *w = (unsigned char *)PyBytes_AS_STRING(out);
    while (k->rcv_queue.head) {
        kseg *s = klist_pop(&k->rcv_queue);
        memcpy(w, s->data, (size_t)s->len);
        w += s->len;
        uint32_t frg = s->frg;
        PyMem_Free(s);
        if (frg == 0) break;
    }
    move_rcv_buf(k);
    if (k->rcv_queue.n < (Py_ssize_t)k->rcv_wnd && recover)
        k->probe |= K_ASK_TELL;
    return out;
}

static PyObject *K_input(KCPCore *k, PyObject *arg) {
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) != 0) return NULL;
    const unsigned char *data = (const unsigned char *)view.buf;
    Py_ssize_t n = view.len;
    if (n < K_OVERHEAD) {
        PyBuffer_Release(&view);
        return PyLong_FromLong(-1);
    }
    uint32_t prev_una = k->snd_una;
    int flag = 0;
    uint32_t maxack = 0;
    Py_ssize_t off = 0;
    int rc = 0;
    while (n - off >= K_OVERHEAD) {
        uint32_t conv = rd_u32(data + off);
        uint32_t cmd = data[off + 4];
        uint32_t frg = data[off + 5];
        uint32_t wnd = rd_u16(data + off + 6);
        uint32_t ts = rd_u32(data + off + 8);
        uint32_t sn = rd_u32(data + off + 12);
        uint32_t una = rd_u32(data + off + 16);
        uint32_t length = rd_u32(data + off + 20);
        off += K_OVERHEAD;
        if (conv != k->conv) { rc = -1; goto out; }
        if ((Py_ssize_t)length > n - off) { rc = -2; goto out; }
        if (cmd != K_CMD_PUSH && cmd != K_CMD_ACK &&
            cmd != K_CMD_WASK && cmd != K_CMD_WINS) { rc = -3; goto out; }
        k->rmt_wnd = wnd;
        parse_una(k, una);
        shrink_buf(k);
        if (cmd == K_CMD_ACK) {
            int32_t rtt = itimediff(k->current, ts);
            if (rtt >= 0) update_ack(k, rtt);
            parse_ack(k, sn);
            shrink_buf(k);
            if (!flag) {
                flag = 1;
                maxack = sn;
            } else if (itimediff(sn, maxack) > 0) {
                maxack = sn;
            }
        } else if (cmd == K_CMD_PUSH) {
            if (itimediff(sn, k->rcv_nxt + k->rcv_wnd) < 0) {
                if (ack_push(k, sn, ts) != 0) {
                    PyBuffer_Release(&view);
                    return PyErr_NoMemory();
                }
                if (itimediff(sn, k->rcv_nxt) >= 0)
                    parse_data(k, sn, frg, data + off, (Py_ssize_t)length);
            }
        } else if (cmd == K_CMD_WASK) {
            k->probe |= K_ASK_TELL;
        }
        off += length;
    }
    if (flag) parse_fastack(k, maxack);
    if (itimediff(k->snd_una, prev_una) > 0 && k->cwnd < k->rmt_wnd) {
        if (k->cwnd < k->ssthresh) {
            k->cwnd++;
            k->incr += k->mss;
        } else {
            if (k->incr < k->mss) k->incr = k->mss;
            k->incr += (k->mss * k->mss) / k->incr + (k->mss / 16);
            if ((k->cwnd + 1) * k->mss <= k->incr)
                k->cwnd = (k->incr + k->mss - 1) / (k->mss ? k->mss : 1);
        }
        if (k->cwnd > k->rmt_wnd) {
            k->cwnd = k->rmt_wnd;
            k->incr = k->rmt_wnd * k->mss;
        }
    }
out:
    PyBuffer_Release(&view);
    return PyLong_FromLong(rc);
}

static PyObject *K_flush(KCPCore *k, PyObject *noarg) {
    if (!k->updated) Py_RETURN_NONE;
    uint32_t current = k->current;
    uint32_t wnd = wnd_unused(k);
    /* 1) pending acks */
    for (Py_ssize_t i = 0; i < k->ackcount; i++) {
        if (kcp_emit(k, K_CMD_ACK, 0, wnd, k->acklist[i * 2 + 1],
                     k->acklist[i * 2], k->rcv_nxt, NULL, 0) != 0)
            return NULL;
    }
    k->ackcount = 0;
    /* 2) zero-window probing */
    if (k->rmt_wnd == 0) {
        if (k->probe_wait == 0) {
            k->probe_wait = K_PROBE_INIT;
            k->ts_probe = current + k->probe_wait;
        } else if (itimediff(current, k->ts_probe) >= 0) {
            if (k->probe_wait < K_PROBE_INIT) k->probe_wait = K_PROBE_INIT;
            k->probe_wait += k->probe_wait / 2;
            if (k->probe_wait > K_PROBE_LIMIT)
                k->probe_wait = K_PROBE_LIMIT;
            k->ts_probe = current + k->probe_wait;
            k->probe |= K_ASK_SEND;
        }
    } else {
        k->ts_probe = 0;
        k->probe_wait = 0;
    }
    if (k->probe & K_ASK_SEND) {
        if (kcp_emit(k, K_CMD_WASK, 0, wnd, 0, 0, k->rcv_nxt, NULL, 0))
            return NULL;
    }
    if (k->probe & K_ASK_TELL) {
        if (kcp_emit(k, K_CMD_WINS, 0, wnd, 0, 0, k->rcv_nxt, NULL, 0))
            return NULL;
    }
    k->probe = 0;
    /* 3) move snd_queue -> snd_buf within the window */
    uint32_t cwnd = k->snd_wnd < k->rmt_wnd ? k->snd_wnd : k->rmt_wnd;
    if (!k->nocwnd && k->cwnd < cwnd) cwnd = k->cwnd;
    while (itimediff(k->snd_nxt, k->snd_una + cwnd) < 0 &&
           k->snd_queue.head) {
        kseg *s = klist_pop(&k->snd_queue);
        s->wnd = wnd;
        s->ts = current;
        s->sn = k->snd_nxt++;
        s->una = k->rcv_nxt;
        s->resendts = current;
        s->rto = k->rx_rto;
        s->fastack = 0;
        s->xmit = 0;
        klist_push(&k->snd_buf, s);
    }
    /* 4) (re)transmit */
    uint32_t resent = k->fastresend > 0 ? (uint32_t)k->fastresend
                                        : 0x7fffffff;
    uint32_t rtomin = k->nodelay_ ? 0 : (k->rx_rto >> 3);
    int lost = 0, change = 0;
    for (kseg *s = k->snd_buf.head; s; s = s->next) {
        int needsend = 0;
        if (s->xmit == 0) {
            needsend = 1;
            s->xmit++;
            s->rto = k->rx_rto;
            s->resendts = current + s->rto + rtomin;
        } else if (itimediff(current, s->resendts) >= 0) {
            needsend = 1;
            s->xmit++;
            k->xmit++;
            if (!k->nodelay_)
                s->rto += s->rto > k->rx_rto ? s->rto : k->rx_rto;
            else
                s->rto += k->rx_rto / 2;
            s->resendts = current + s->rto;
            lost = 1;
        } else if (s->fastack >= resent) {
            needsend = 1;
            s->xmit++;
            s->fastack = 0;
            s->resendts = current + s->rto;
            change = 1;
        }
        if (needsend) {
            s->ts = current;
            s->wnd = wnd;
            s->una = k->rcv_nxt;
            if (kcp_emit(k, K_CMD_PUSH, s->frg, wnd, s->ts, s->sn,
                         s->una, s->data, s->len) != 0)
                return NULL;
            if (s->xmit >= k->dead_link) k->state = -1;
        }
    }
    if (kcp_outflush(k) != 0) return NULL;
    /* 5) congestion state */
    if (change) {
        uint32_t inflight = k->snd_nxt - k->snd_una;
        k->ssthresh = inflight / 2;
        if (k->ssthresh < K_THRESH_MIN) k->ssthresh = K_THRESH_MIN;
        k->cwnd = k->ssthresh + resent;
        k->incr = k->cwnd * k->mss;
    }
    if (lost) {
        k->ssthresh = cwnd / 2;
        if (k->ssthresh < K_THRESH_MIN) k->ssthresh = K_THRESH_MIN;
        k->cwnd = 1;
        k->incr = k->mss;
    }
    if (k->cwnd < 1) {
        k->cwnd = 1;
        k->incr = k->mss;
    }
    Py_RETURN_NONE;
}

static PyObject *K_update(KCPCore *k, PyObject *arg) {
    unsigned long cur = PyLong_AsUnsignedLongMask(arg);
    if (PyErr_Occurred()) return NULL;
    k->current = (uint32_t)cur;
    if (!k->updated) {
        k->updated = 1;
        k->ts_flush = k->current;
    }
    int32_t slap = itimediff(k->current, k->ts_flush);
    if (slap >= 10000 || slap < -10000) {
        k->ts_flush = k->current;
        slap = 0;
    }
    if (slap >= 0) {
        k->ts_flush += k->interval_;
        if (itimediff(k->current, k->ts_flush) >= 0)
            k->ts_flush = k->current + k->interval_;
        return K_flush(k, NULL);
    }
    Py_RETURN_NONE;
}

static PyObject *K_check(KCPCore *k, PyObject *arg) {
    unsigned long cur = PyLong_AsUnsignedLongMask(arg);
    if (PyErr_Occurred()) return NULL;
    uint32_t current = (uint32_t)cur;
    if (!k->updated) return PyLong_FromUnsignedLong(current);
    uint32_t ts_flush = k->ts_flush;
    int32_t slap = itimediff(current, ts_flush);
    if (slap >= 10000 || slap < -10000) ts_flush = current;
    if (itimediff(current, ts_flush) >= 0)
        return PyLong_FromUnsignedLong(current);
    int32_t tm_packet = 0x7fffffff;
    for (kseg *s = k->snd_buf.head; s; s = s->next) {
        int32_t diff = itimediff(s->resendts, current);
        if (diff <= 0) return PyLong_FromUnsignedLong(current);
        if (diff < tm_packet) tm_packet = diff;
    }
    int32_t minimal = itimediff(ts_flush, current);
    if (tm_packet < minimal) minimal = tm_packet;
    if ((int32_t)k->interval_ < minimal) minimal = (int32_t)k->interval_;
    return PyLong_FromUnsignedLong(current + (uint32_t)minimal);
}

static PyObject *K_set_nodelay(KCPCore *k, PyObject *args) {
    int nd, interval, resend, nc;
    if (!PyArg_ParseTuple(args, "iiii", &nd, &interval, &resend, &nc))
        return NULL;
    if (nd >= 0) {
        k->nodelay_ = nd;
        k->rx_minrto = nd ? K_RTO_NDL : K_RTO_MIN;
    }
    if (interval >= 0) {
        if (interval < 10) interval = 10;
        if (interval > 5000) interval = 5000;
        k->interval_ = (uint32_t)interval;
    }
    if (resend >= 0) k->fastresend = resend;
    if (nc >= 0) k->nocwnd = nc;
    Py_RETURN_NONE;
}

static PyObject *K_set_wndsize(KCPCore *k, PyObject *args) {
    int snd, rcv;
    if (!PyArg_ParseTuple(args, "ii", &snd, &rcv)) return NULL;
    if (snd > 0) k->snd_wnd = (uint32_t)snd;
    if (rcv > 0)
        k->rcv_wnd = (uint32_t)(rcv > K_WND_RCV ? rcv : K_WND_RCV);
    Py_RETURN_NONE;
}

static PyObject *K_set_mtu(KCPCore *k, PyObject *arg) {
    long mtu = PyLong_AsLong(arg);
    if (PyErr_Occurred()) return NULL;
    if (mtu < 50 || mtu < K_OVERHEAD) {
        PyErr_SetString(PyExc_ValueError, "mtu too small");
        return NULL;
    }
    /* GROW-only assembly buffer: segments queued before an mtu SHRINK
     * keep their old (larger) length, and kcp_emit's overflow-flush
     * check is against the new mtu — emitting such a segment into a
     * shrunken buffer would be a heap overflow (code-review r5). */
    if ((size_t)mtu + K_OVERHEAD > k->obuf_cap) {
        unsigned char *nb = (unsigned char *)PyMem_Realloc(
            k->obuf, (size_t)mtu + K_OVERHEAD);
        if (nb == NULL) return PyErr_NoMemory();
        k->obuf = nb;
        k->obuf_cap = (size_t)mtu + K_OVERHEAD;
    }
    k->mtu = (uint32_t)mtu;
    k->mss = k->mtu - K_OVERHEAD;
    Py_RETURN_NONE;
}

static PyObject *K_waiting_send(KCPCore *k, PyObject *noarg) {
    return PyLong_FromSsize_t(k->snd_buf.n + k->snd_queue.n);
}

static PyObject *K_idle(KCPCore *k, PyObject *noarg) {
    if (k->snd_buf.n == 0 && k->snd_queue.n == 0 && k->ackcount == 0 &&
        k->probe == 0 && k->rmt_wnd > 0)
        Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

/* --- type plumbing ------------------------------------------------------- */

static int K_init(KCPCore *k, PyObject *args, PyObject *kwds) {
    unsigned long conv;
    PyObject *output;
    static char *kwlist[] = {"conv", "output", NULL};
    if (!PyArg_ParseTupleAndKeywords(args, kwds, "kO", kwlist, &conv,
                                     &output))
        return -1;
    if (!PyCallable_Check(output)) {
        PyErr_SetString(PyExc_TypeError, "output must be callable");
        return -1;
    }
    Py_INCREF(output);
    Py_XSETREF(k->output, output);
    k->conv = (uint32_t)conv;
    k->ssthresh = K_THRESH_INIT;
    k->rx_rto = K_RTO_DEF;
    k->rx_minrto = K_RTO_MIN;
    k->snd_wnd = K_WND_SND;
    k->rcv_wnd = K_WND_RCV;
    k->rmt_wnd = K_WND_RCV;
    k->mtu = K_MTU_DEF;
    k->mss = K_MTU_DEF - K_OVERHEAD;
    k->interval_ = K_INTERVAL;
    k->ts_flush = K_INTERVAL;
    k->dead_link = K_DEADLINK;
    k->obuf = (unsigned char *)PyMem_Malloc(K_MTU_DEF + K_OVERHEAD);
    if (k->obuf == NULL) {
        PyErr_NoMemory();
        return -1;
    }
    k->obuf_cap = K_MTU_DEF + K_OVERHEAD;
    return 0;
}

/* Cyclic-GC support (code-review r5): the session layer passes a BOUND
 * METHOD as output, creating the cycle connection -> KCPCore -> output
 * -> connection; without traverse/clear every closed session would leak
 * its whole object graph — the exact churn workload this port serves. */
static int K_traverse(KCPCore *k, visitproc visit, void *arg) {
    Py_VISIT(k->output);
    return 0;
}

static int K_clear(KCPCore *k) {
    Py_CLEAR(k->output);
    return 0;
}

static void K_dealloc(KCPCore *k) {
    PyObject_GC_UnTrack(k);
    Py_XDECREF(k->output);
    klist_clear(&k->snd_queue);
    klist_clear(&k->rcv_queue);
    klist_clear(&k->snd_buf);
    klist_clear(&k->rcv_buf);
    PyMem_Free(k->acklist);
    PyMem_Free(k->obuf);
    Py_TYPE(k)->tp_free((PyObject *)k);
}

static PyMethodDef K_methods[] = {
    {"send", (PyCFunction)K_send, METH_O, "queue user bytes"},
    {"recv", (PyCFunction)K_recv, METH_NOARGS, "one message or None"},
    {"input", (PyCFunction)K_input, METH_O, "feed a received datagram"},
    {"update", (PyCFunction)K_update, METH_O, "clock the protocol (ms)"},
    {"check", (PyCFunction)K_check, METH_O, "next work deadline (ms)"},
    {"flush", (PyCFunction)K_flush, METH_NOARGS, "emit pending output"},
    {"set_nodelay", (PyCFunction)K_set_nodelay, METH_VARARGS, ""},
    {"set_wndsize", (PyCFunction)K_set_wndsize, METH_VARARGS, ""},
    {"set_mtu", (PyCFunction)K_set_mtu, METH_O, ""},
    {"waiting_send", (PyCFunction)K_waiting_send, METH_NOARGS, ""},
    {"idle", (PyCFunction)K_idle, METH_NOARGS, ""},
    {NULL, NULL, 0, NULL},
};

#define K_GETSET_U32(name, field)                                        \
    static PyObject *K_get_##name(KCPCore *k, void *c) {                 \
        return PyLong_FromUnsignedLong(k->field);                        \
    }

K_GETSET_U32(conv, conv)
K_GETSET_U32(rmt_wnd, rmt_wnd)
K_GETSET_U32(rx_rto, rx_rto)
K_GETSET_U32(snd_una, snd_una)
K_GETSET_U32(snd_nxt, snd_nxt)
K_GETSET_U32(rcv_nxt, rcv_nxt)
K_GETSET_U32(probe_wait, probe_wait)
K_GETSET_U32(mss, mss)
K_GETSET_U32(interval, interval_)

static PyObject *K_get_state(KCPCore *k, void *c) {
    return PyLong_FromLong(k->state);
}

static PyObject *K_get_updated(KCPCore *k, void *c) {
    return PyBool_FromLong(k->updated);
}

static PyObject *K_get_stream(KCPCore *k, void *c) {
    return PyBool_FromLong(k->stream);
}

static int K_set_stream(KCPCore *k, PyObject *v, void *c) {
    int b = PyObject_IsTrue(v);
    if (b < 0) return -1;
    k->stream = b;
    return 0;
}

static PyObject *K_get_current(KCPCore *k, void *c) {
    return PyLong_FromUnsignedLong(k->current);
}

static int K_set_current(KCPCore *k, PyObject *v, void *c) {
    unsigned long cur = PyLong_AsUnsignedLongMask(v);
    if (PyErr_Occurred()) return -1;
    k->current = (uint32_t)cur;
    return 0;
}

static PyObject *K_get_has_acks(KCPCore *k, void *c) {
    return PyBool_FromLong(k->ackcount > 0);
}

static PyObject *K_get_snd_buf_len(KCPCore *k, void *c) {
    return PyLong_FromSsize_t(k->snd_buf.n);
}

static PyObject *K_get_snd_queue_len(KCPCore *k, void *c) {
    return PyLong_FromSsize_t(k->snd_queue.n);
}

static PyGetSetDef K_getset[] = {
    {"conv", (getter)K_get_conv, NULL, NULL, NULL},
    {"rmt_wnd", (getter)K_get_rmt_wnd, NULL, NULL, NULL},
    {"rx_rto", (getter)K_get_rx_rto, NULL, NULL, NULL},
    {"snd_una", (getter)K_get_snd_una, NULL, NULL, NULL},
    {"snd_nxt", (getter)K_get_snd_nxt, NULL, NULL, NULL},
    {"rcv_nxt", (getter)K_get_rcv_nxt, NULL, NULL, NULL},
    {"probe_wait", (getter)K_get_probe_wait, NULL, NULL, NULL},
    {"mss", (getter)K_get_mss, NULL, NULL, NULL},
    {"interval", (getter)K_get_interval, NULL, NULL, NULL},
    {"state", (getter)K_get_state, NULL, NULL, NULL},
    {"updated", (getter)K_get_updated, NULL, NULL, NULL},
    {"stream", (getter)K_get_stream, (setter)K_set_stream, NULL, NULL},
    {"current", (getter)K_get_current, (setter)K_set_current, NULL, NULL},
    {"has_acks", (getter)K_get_has_acks, NULL, NULL, NULL},
    {"snd_buf_len", (getter)K_get_snd_buf_len, NULL, NULL, NULL},
    {"snd_queue_len", (getter)K_get_snd_queue_len, NULL, NULL, NULL},
    {NULL, NULL, NULL, NULL, NULL},
};

static PyTypeObject KCPCoreType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_kcpcore.KCPCore",
    .tp_basicsize = sizeof(KCPCore),
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC,
    .tp_new = PyType_GenericNew,
    .tp_init = (initproc)K_init,
    .tp_traverse = (traverseproc)K_traverse,
    .tp_clear = (inquiry)K_clear,
    .tp_dealloc = (destructor)K_dealloc,
    .tp_methods = K_methods,
    .tp_getset = K_getset,
    .tp_doc = "KCP control block (C hot path; parity with kcp.py's KCP)",
};

/* --- GF(256) Reed-Solomon row mat-mul (FEC hot loop, netutil/fec.py) ----- */

static unsigned char GF_MUL[256][256];

static void gf_init(void) {
    unsigned short exp[512];
    unsigned char log[256];
    unsigned x = 1;
    memset(log, 0, sizeof(log));
    for (int i = 0; i < 255; i++) {
        exp[i] = (unsigned short)x;
        log[x] = (unsigned char)i;
        x <<= 1;
        if (x & 0x100) x ^= 0x11d;
    }
    for (int i = 255; i < 512; i++) exp[i] = exp[i - 255];
    for (int a = 1; a < 256; a++)
        for (int b = 1; b < 256; b++)
            GF_MUL[a][b] = (unsigned char)exp[log[a] + log[b]];
}

/* rs_matmul(rows, shards, length) -> list[bytes]
 *   rows: sequence of sequences of GF coefficients (one per shard)
 *   shards: sequence of bytes objects, each >= length (extra ignored;
 *           shorter shards are zero-padded implicitly)
 * Returns one length-sized bytes per row: XOR_i coeff[i] * shard[i]. */
static PyObject *rs_matmul(PyObject *self, PyObject *args) {
    PyObject *rows, *shards;
    Py_ssize_t length;
    if (!PyArg_ParseTuple(args, "OOn", &rows, &shards, &length))
        return NULL;
    PyObject *rows_f = PySequence_Fast(rows, "rows must be a sequence");
    if (rows_f == NULL) return NULL;
    PyObject *shards_f =
        PySequence_Fast(shards, "shards must be a sequence");
    if (shards_f == NULL) {
        Py_DECREF(rows_f);
        return NULL;
    }
    Py_ssize_t nrows = PySequence_Fast_GET_SIZE(rows_f);
    Py_ssize_t nsh = PySequence_Fast_GET_SIZE(shards_f);
    PyObject *out = PyList_New(nrows);
    if (out == NULL) goto fail;
    for (Py_ssize_t r = 0; r < nrows; r++) {
        PyObject *row_f = PySequence_Fast(
            PySequence_Fast_GET_ITEM(rows_f, r), "row must be a sequence");
        if (row_f == NULL) goto fail;
        if (PySequence_Fast_GET_SIZE(row_f) < nsh) {
            Py_DECREF(row_f);
            PyErr_SetString(PyExc_ValueError, "row shorter than shards");
            goto fail;
        }
        PyObject *acc_obj = PyBytes_FromStringAndSize(NULL, length);
        if (acc_obj == NULL) {
            Py_DECREF(row_f);
            goto fail;
        }
        unsigned char *acc = (unsigned char *)PyBytes_AS_STRING(acc_obj);
        memset(acc, 0, (size_t)length);
        for (Py_ssize_t i = 0; i < nsh; i++) {
            long c = PyLong_AsLong(PySequence_Fast_GET_ITEM(row_f, i));
            if (c == -1 && PyErr_Occurred()) {
                Py_DECREF(row_f);
                Py_DECREF(acc_obj);
                goto fail;
            }
            if (c == 0) continue;
            if (c < 0 || c > 255) {
                Py_DECREF(row_f);
                Py_DECREF(acc_obj);
                PyErr_SetString(PyExc_ValueError, "coeff out of GF(256)");
                goto fail;
            }
            char *sb;
            Py_ssize_t sl;
            if (PyBytes_AsStringAndSize(
                    PySequence_Fast_GET_ITEM(shards_f, i), &sb, &sl) != 0) {
                Py_DECREF(row_f);
                Py_DECREF(acc_obj);
                goto fail;
            }
            Py_ssize_t n = sl < length ? sl : length;
            const unsigned char *tbl = GF_MUL[c];
            const unsigned char *s = (const unsigned char *)sb;
            if (c == 1) {
                for (Py_ssize_t j = 0; j < n; j++) acc[j] ^= s[j];
            } else {
                for (Py_ssize_t j = 0; j < n; j++) acc[j] ^= tbl[s[j]];
            }
        }
        Py_DECREF(row_f);
        PyList_SET_ITEM(out, r, acc_obj);
    }
    Py_DECREF(rows_f);
    Py_DECREF(shards_f);
    return out;
fail:
    Py_DECREF(rows_f);
    Py_DECREF(shards_f);
    Py_XDECREF(out);
    return NULL;
}

static PyMethodDef module_methods[] = {
    {"rs_matmul", rs_matmul, METH_VARARGS,
     "rs_matmul(rows, shards, length) -> list[bytes] (GF(256) XOR-dot)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_kcpcore",
    "C KCP control block", -1, module_methods,
};

PyMODINIT_FUNC PyInit__kcpcore(void) {
    gf_init();
    if (PyType_Ready(&KCPCoreType) < 0) return NULL;
    PyObject *m = PyModule_Create(&moduledef);
    if (m == NULL) return NULL;
    Py_INCREF(&KCPCoreType);
    if (PyModule_AddObject(m, "KCPCore", (PyObject *)&KCPCoreType) < 0) {
        Py_DECREF(&KCPCoreType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}

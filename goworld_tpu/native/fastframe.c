/* _fastframe — C hot path for the wire framing every process runs.
 *
 * The reference's runtime is a compiled binary (Go); the Python port's
 * per-packet costs (two asyncio awaits + struct packs + slicing per
 * packet) dominate gate/dispatcher CPU at fleet scale (BENCH_NOTES:
 * control-plane profile at 100 bots — framing + zlib + socket sends).
 * This module batch-parses an entire received chunk in one call and
 * builds framed send buffers without intermediate Python objects.
 *
 * Wire format (netutil/packet_conn.py, PacketConnection.go:50-186):
 *   [u32 LE length | bit31 = zlib flag][u16 LE msgtype][payload]
 * Length counts msgtype + payload (the post-inflate size must also stay
 * within max_packet — decompression-bomb guard, matching the Python
 * recv_packet's bounded inflate).
 *
 * API (mirrored exactly by native/pyframe.py — the parity fuzz suite in
 * tests/test_native.py drives both):
 *   split(data: bytes, max_packet: int) -> (frames, consumed, error)
 *       frames = list[(msgtype: int, payload: bytes)], consumed = int
 *       (caller keeps data[consumed:] as the remainder), error = None or
 *       a str describing the malformed frame parsing STOPPED at (bad
 *       length, bad zlib stream, inflate overflow). Frames before the
 *       malformed one are still returned so no valid packet is lost to a
 *       chunk boundary; the caller treats error as connection-fatal.
 *   pack(msgtype: int, payload: bytes, compress: bool, threshold: int,
 *        max_packet: int) -> bytes
 *       One framed buffer; compresses at level 1 when enabled, the body
 *       reaches threshold, and deflate actually shrinks it. ValueError
 *       on msgtype outside u16 or oversize body.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>
#include <zlib.h>

#define COMPRESSED_BIT 0x80000000u
#define LEN_MASK 0x7fffffffu

static uint32_t rd_u32le(const unsigned char *p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
           ((uint32_t)p[3] << 24);
}

/* Bounded inflate of src[0..n) into a fresh bytes object of at most cap
 * bytes. The output buffer starts small (most compressed packets are
 * small) and grows geometrically up to cap — never a cap-sized (25 MB)
 * allocation per tiny frame. Returns NULL with ValueError set on any
 * zlib error or cap overflow. */
static PyObject *inflate_bounded(const unsigned char *src, Py_ssize_t n,
                                 Py_ssize_t cap) {
    Py_ssize_t size = n * 4 + 64;
    if (size > cap) size = cap;
    for (;;) {
        PyObject *out = PyBytes_FromStringAndSize(NULL, size);
        if (out == NULL) return NULL;
        z_stream zs;
        memset(&zs, 0, sizeof(zs));
        if (inflateInit(&zs) != Z_OK) {
            Py_DECREF(out);
            PyErr_SetString(PyExc_ValueError, "inflateInit failed");
            return NULL;
        }
        zs.next_in = (Bytef *)src;
        zs.avail_in = (uInt)n;
        zs.next_out = (Bytef *)PyBytes_AS_STRING(out);
        zs.avail_out = (uInt)size;
        int rc = inflate(&zs, Z_FINISH);
        Py_ssize_t produced = size - (Py_ssize_t)zs.avail_out;
        inflateEnd(&zs);
        if (rc == Z_STREAM_END) {
            if (_PyBytes_Resize(&out, produced) != 0) return NULL;
            return out;
        }
        Py_DECREF(out);
        int ran_out = (rc == Z_BUF_ERROR || rc == Z_OK) && zs.avail_out == 0;
        if (ran_out && size < cap) {
            size = size * 4 <= cap ? size * 4 : cap; /* grow, retry */
            continue;
        }
        PyErr_SetString(PyExc_ValueError,
                        ran_out ? "compressed packet exceeds size cap"
                                : "bad compressed packet");
        return NULL;
    }
}

static PyObject *fastframe_split(PyObject *self, PyObject *args) {
    Py_buffer view;
    Py_ssize_t max_packet;
    if (!PyArg_ParseTuple(args, "y*n", &view, &max_packet)) return NULL;
    const unsigned char *buf = (const unsigned char *)view.buf;
    Py_ssize_t len = view.len;
    Py_ssize_t off = 0;
    const char *err = NULL; /* static message: stop-and-report, not raise */
    PyObject *err_obj = NULL; /* owned message from a raising helper */

    PyObject *frames = PyList_New(0);
    if (frames == NULL) {
        PyBuffer_Release(&view);
        return NULL;
    }
    while (len - off >= 4) {
        uint32_t raw = rd_u32le(buf + off);
        int compressed = (raw & COMPRESSED_BIT) != 0;
        Py_ssize_t body_len = (Py_ssize_t)(raw & LEN_MASK);
        if (body_len < 2 || body_len > max_packet) {
            err_obj = PyUnicode_FromFormat("bad packet length %zd", body_len);
            if (err_obj == NULL) goto fail;
            break;
        }
        if (len - off - 4 < body_len) break; /* incomplete frame */
        const unsigned char *body = buf + off + 4;
        PyObject *payload;
        unsigned int msgtype;
        if (compressed) {
            PyObject *inflated =
                inflate_bounded(body, body_len, max_packet);
            if (inflated == NULL) {
                /* Convert the helper's ValueError into the stop-and-
                 * report contract (frames so far still delivered). */
                PyObject *tp, *val, *tb;
                PyErr_Fetch(&tp, &val, &tb);
                err_obj = val ? PyObject_Str(val) : NULL;
                Py_XDECREF(tp);
                Py_XDECREF(val);
                Py_XDECREF(tb);
                if (err_obj == NULL) err = "bad compressed packet";
                break;
            }
            Py_ssize_t ilen = PyBytes_GET_SIZE(inflated);
            if (ilen < 2) {
                Py_DECREF(inflated);
                err = "bad decompressed length";
                break;
            }
            const unsigned char *ib =
                (const unsigned char *)PyBytes_AS_STRING(inflated);
            msgtype = (unsigned int)ib[0] | ((unsigned int)ib[1] << 8);
            payload = PyBytes_FromStringAndSize((const char *)ib + 2,
                                                ilen - 2);
            Py_DECREF(inflated);
        } else {
            msgtype = (unsigned int)body[0] | ((unsigned int)body[1] << 8);
            payload = PyBytes_FromStringAndSize((const char *)body + 2,
                                                body_len - 2);
        }
        if (payload == NULL) goto fail;
        PyObject *tup = Py_BuildValue("(IN)", msgtype, payload);
        if (tup == NULL) goto fail;
        int rc = PyList_Append(frames, tup);
        Py_DECREF(tup);
        if (rc != 0) goto fail;
        off += 4 + body_len;
    }
    PyBuffer_Release(&view);
    if (err_obj != NULL) return Py_BuildValue("(NnN)", frames, off, err_obj);
    if (err != NULL) return Py_BuildValue("(Nns)", frames, off, err);
    return Py_BuildValue("(NnO)", frames, off, Py_None);
fail:
    Py_XDECREF(err_obj);
    Py_DECREF(frames);
    PyBuffer_Release(&view);
    return NULL;
}

static PyObject *fastframe_pack(PyObject *self, PyObject *args) {
    unsigned int msgtype;
    Py_buffer view;
    int compress;
    Py_ssize_t threshold, max_packet;
    if (!PyArg_ParseTuple(args, "Iy*pnn", &msgtype, &view, &compress,
                          &threshold, &max_packet))
        return NULL;
    if (msgtype > 0xFFFF) {
        PyBuffer_Release(&view);
        PyErr_Format(PyExc_ValueError, "msgtype %u out of u16 range",
                     msgtype);
        return NULL;
    }
    Py_ssize_t plen = view.len;
    Py_ssize_t body_len = 2 + plen;
    if (body_len > max_packet) {
        PyBuffer_Release(&view);
        PyErr_Format(PyExc_ValueError, "packet too large: %zd", body_len);
        return NULL;
    }
    uint32_t flag = 0;

    if (compress && body_len >= threshold) {
        /* Deflate [msgtype][payload] at level 1 (KCP/zlib parity with the
         * Python path); keep only if it actually shrinks. */
        uLong bound = compressBound((uLong)body_len);
        PyObject *tmp = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)bound);
        if (tmp == NULL) {
            PyBuffer_Release(&view);
            return NULL;
        }
        unsigned char hdr[2] = {(unsigned char)(msgtype & 0xff),
                                (unsigned char)((msgtype >> 8) & 0xff)};
        z_stream zs;
        memset(&zs, 0, sizeof(zs));
        int ok = deflateInit(&zs, 1) == Z_OK;
        Py_ssize_t clen = 0;
        if (ok) {
            zs.next_out = (Bytef *)PyBytes_AS_STRING(tmp);
            zs.avail_out = (uInt)bound;
            zs.next_in = hdr;
            zs.avail_in = 2;
            ok = deflate(&zs, Z_NO_FLUSH) == Z_OK;
            if (ok) {
                zs.next_in = (Bytef *)view.buf;
                zs.avail_in = (uInt)plen;
                ok = deflate(&zs, Z_FINISH) == Z_STREAM_END;
            }
            clen = (Py_ssize_t)zs.total_out;
            deflateEnd(&zs);
        }
        if (ok && clen < body_len) {
            PyObject *out = PyBytes_FromStringAndSize(NULL, 4 + clen);
            if (out == NULL) {
                Py_DECREF(tmp);
                PyBuffer_Release(&view);
                return NULL;
            }
            unsigned char *w = (unsigned char *)PyBytes_AS_STRING(out);
            uint32_t raw = (uint32_t)clen | COMPRESSED_BIT;
            w[0] = raw & 0xff;
            w[1] = (raw >> 8) & 0xff;
            w[2] = (raw >> 16) & 0xff;
            w[3] = (raw >> 24) & 0xff;
            memcpy(w + 4, PyBytes_AS_STRING(tmp), clen);
            Py_DECREF(tmp);
            PyBuffer_Release(&view);
            return out;
        }
        Py_DECREF(tmp);
        (void)flag;
    }

    PyObject *out = PyBytes_FromStringAndSize(NULL, 4 + body_len);
    if (out == NULL) {
        PyBuffer_Release(&view);
        return NULL;
    }
    unsigned char *w = (unsigned char *)PyBytes_AS_STRING(out);
    uint32_t raw = (uint32_t)body_len;
    w[0] = raw & 0xff;
    w[1] = (raw >> 8) & 0xff;
    w[2] = (raw >> 16) & 0xff;
    w[3] = (raw >> 24) & 0xff;
    w[4] = msgtype & 0xff;
    w[5] = (msgtype >> 8) & 0xff;
    memcpy(w + 6, view.buf, plen);
    PyBuffer_Release(&view);
    return out;
}

static PyMethodDef methods[] = {
    {"split", fastframe_split, METH_VARARGS,
     "split(data, max_packet) -> (frames, consumed, error)"},
    {"pack", fastframe_pack, METH_VARARGS,
     "pack(msgtype, payload, compress, threshold, max_packet) -> bytes"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastframe",
    "C hot path for goworld wire framing", -1, methods,
};

PyMODINIT_FUNC PyInit__fastframe(void) { return PyModule_Create(&moduledef); }

/* _fastframe — C hot path for the wire framing every process runs.
 *
 * The reference's runtime is a compiled binary (Go); the Python port's
 * per-packet costs (two asyncio awaits + struct packs + slicing per
 * packet) dominate gate/dispatcher CPU at fleet scale (BENCH_NOTES:
 * control-plane profile at 100 bots — framing + zlib + socket sends).
 * This module batch-parses an entire received chunk in one call and
 * builds framed send buffers without intermediate Python objects.
 *
 * Wire format (netutil/packet_conn.py, PacketConnection.go:50-186):
 *   [u32 LE length | bit31 = zlib flag | bit30 = snappy flag]
 *   [u16 LE msgtype][payload]
 * Length counts msgtype + payload (the post-decompress size must also
 * stay within max_packet — decompression-bomb guard, matching the Python
 * recv_packet's bounded inflate).
 *
 * Snappy is the reference's actual gate↔client codec (ClientProxy.go:
 * 42-45); the block-format codec below is from scratch against the
 * public Snappy format description (varint uncompressed-length preamble,
 * then literal/copy elements) — the library isn't in the image.
 *
 * API (mirrored exactly by native/pyframe.py — the parity fuzz suite in
 * tests/test_native.py drives both):
 *   split(data: bytes, max_packet: int) -> (frames, consumed, error)
 *       frames = list[(msgtype: int, payload: bytes)], consumed = int
 *       (caller keeps data[consumed:] as the remainder), error = None or
 *       a str describing the malformed frame parsing STOPPED at (bad
 *       length, bad compressed stream, bounded-decompress overflow).
 *       Frames before the malformed one are still returned so no valid
 *       packet is lost to a chunk boundary; the caller treats error as
 *       connection-fatal.
 *   pack(msgtype: int, payload: bytes, compress: int, threshold: int,
 *        max_packet: int) -> bytes
 *       One framed buffer; compress = 0 off, 1 zlib level 1, 2 snappy —
 *       applied when the body reaches threshold and the codec actually
 *       shrinks it. ValueError on msgtype outside u16 or oversize body.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>
#include <zlib.h>

#define COMPRESSED_BIT 0x80000000u /* zlib */
#define SNAPPY_BIT 0x40000000u
#define LEN_MASK 0x3fffffffu

static uint32_t rd_u32le(const unsigned char *p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
           ((uint32_t)p[3] << 24);
}

/* --- snappy block codec -------------------------------------------------- */

#define SNAPPY_BLOCK 32768 /* fragment size: offsets always fit 2 bytes */
#define SNAPPY_HASH_BITS 14

static unsigned snappy_hash(uint32_t v) {
    return (unsigned)((v * 0x1e35a7bdu) >> (32 - SNAPPY_HASH_BITS));
}

/* Every emit helper is HARD-BOUNDED by the caller's buffer end and
 * signals overflow by returning NULL: pack() only keeps compressed
 * output that is SMALLER than the input, so the encoder writes into an
 * input-sized scratch and treats hitting its end as "incompressible" —
 * no worst-case-expansion arithmetic to get wrong (code-review r5
 * reproduced a heap overrun in the previous bound-based version with a
 * crafted +1-byte-per-65 adversarial payload). */
static unsigned char *snappy_emit_literal(unsigned char *w,
                                          const unsigned char *end,
                                          const unsigned char *s,
                                          Py_ssize_t len) {
    if (w == NULL || len <= 0) return w;
    Py_ssize_t n1 = len - 1;
    if (end - w < len + 3) return NULL;
    if (n1 < 60) {
        *w++ = (unsigned char)(n1 << 2);
    } else if (n1 < 0x100) {
        *w++ = 60 << 2;
        *w++ = (unsigned char)n1;
    } else { /* blocks cap at 32768: two bytes always suffice */
        *w++ = 61 << 2;
        *w++ = (unsigned char)(n1 & 0xff);
        *w++ = (unsigned char)((n1 >> 8) & 0xff);
    }
    memcpy(w, s, (size_t)len);
    return w + len;
}

static unsigned char *snappy_emit_copy(unsigned char *w,
                                       const unsigned char *end,
                                       Py_ssize_t off, Py_ssize_t len) {
    if (w == NULL) return NULL;
    if (end - w < 3 * (len / 64 + 2)) return NULL;
    while (len >= 68) {
        *w++ = (63 << 2) | 2;
        *w++ = (unsigned char)(off & 0xff);
        *w++ = (unsigned char)((off >> 8) & 0xff);
        len -= 64;
    }
    if (len > 64) {
        *w++ = (59 << 2) | 2;
        *w++ = (unsigned char)(off & 0xff);
        *w++ = (unsigned char)((off >> 8) & 0xff);
        len -= 60;
    }
    if (len <= 11 && off < 2048) {
        *w++ = (unsigned char)(1 | ((len - 4) << 2) | ((off >> 8) << 5));
        *w++ = (unsigned char)(off & 0xff);
    } else {
        *w++ = (unsigned char)(((len - 1) << 2) | 2);
        *w++ = (unsigned char)(off & 0xff);
        *w++ = (unsigned char)((off >> 8) & 0xff);
    }
    return w;
}

/* Greedy 4-byte-hash matcher over 32 KiB fragments (same strategy as the
 * Python reference implementation — byte-identical output is NOT required
 * between the two encoders, only decode(encode(x)) == x on both; the
 * parity fuzz cross-decodes to enforce exactly that). Returns the
 * compressed size, or -1 when the output would reach dst_cap (caller
 * ships uncompressed — identical outcome to "didn't shrink"). */
static Py_ssize_t snappy_encode(const unsigned char *src, Py_ssize_t n,
                                unsigned char *dst, Py_ssize_t dst_cap) {
    unsigned char *w = dst;
    const unsigned char *end = dst + dst_cap;
    Py_ssize_t v = n;
    while (v >= 0x80) {
        if (w >= end) return -1;
        *w++ = (unsigned char)((v & 0x7f) | 0x80);
        v >>= 7;
    }
    if (w >= end) return -1;
    *w++ = (unsigned char)v;
    static _Thread_local uint16_t table[1 << SNAPPY_HASH_BITS];
    Py_ssize_t i = 0;
    while (i < n) {
        Py_ssize_t base = i;
        Py_ssize_t block_end =
            i + SNAPPY_BLOCK < n ? i + SNAPPY_BLOCK : n;
        memset(table, 0xff, sizeof(table));
        Py_ssize_t lit_start = i;
        while (i < block_end) {
            if (block_end - i < 4) {
                i = block_end;
                break;
            }
            uint32_t key = rd_u32le(src + i);
            unsigned h = snappy_hash(key);
            Py_ssize_t cand = table[h] == 0xffff
                                  ? -1
                                  : base + (Py_ssize_t)table[h];
            table[h] = (uint16_t)(i - base);
            if (cand >= base && cand < i &&
                rd_u32le(src + cand) == key) {
                w = snappy_emit_literal(w, end, src + lit_start,
                                        i - lit_start);
                Py_ssize_t m = i + 4, c = cand + 4;
                while (m < block_end && src[m] == src[c]) {
                    m++;
                    c++;
                }
                w = snappy_emit_copy(w, end, i - cand, m - i);
                if (w == NULL) return -1;
                i = m;
                lit_start = i;
            } else {
                i++;
            }
        }
        w = snappy_emit_literal(w, end, src + lit_start,
                                block_end - lit_start);
        if (w == NULL) return -1;
    }
    return w - dst;
}

/* Bounded snappy decode into a fresh bytes object; NULL + ValueError on
 * malformed input or when the declared size exceeds cap (bomb guard). */
static PyObject *snappy_decode_bounded(const unsigned char *src,
                                       Py_ssize_t n, Py_ssize_t cap) {
    Py_ssize_t i = 0;
    uint64_t ulen = 0;
    int shift = 0;
    for (;;) {
        if (i >= n || shift > 31) {
            PyErr_SetString(PyExc_ValueError, "bad snappy preamble");
            return NULL;
        }
        unsigned char b = src[i++];
        ulen |= (uint64_t)(b & 0x7f) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
    }
    if ((Py_ssize_t)ulen > cap) {
        PyErr_SetString(PyExc_ValueError,
                        "compressed packet exceeds size cap");
        return NULL;
    }
    /* Grow geometrically toward the declared size instead of trusting a
     * 5-byte frame's preamble with a cap-sized allocation up front —
     * same anti-bomb allocation profile as inflate_bounded above
     * (code-review r5). Every write is still bounded by `total`, so a
     * stream that lies about ulen fails validation, never overruns. */
    Py_ssize_t total = (Py_ssize_t)ulen;
    Py_ssize_t size = n * 4 + 64;
    if (size > total) size = total;
    PyObject *out_obj = PyBytes_FromStringAndSize(NULL, size);
    if (out_obj == NULL) return NULL;
    unsigned char *out = (unsigned char *)PyBytes_AS_STRING(out_obj);
    Py_ssize_t pos = 0;
#define SNAPPY_ENSURE(need)                                               \
    do {                                                                  \
        if (pos + (need) > total) goto bad;                               \
        if (pos + (need) > size) {                                        \
            while (size < pos + (need))                                   \
                size = size * 4 <= total ? size * 4 : total;              \
            if (_PyBytes_Resize(&out_obj, size) != 0) return NULL;        \
            out = (unsigned char *)PyBytes_AS_STRING(out_obj);            \
        }                                                                 \
    } while (0)
    while (i < n) {
        unsigned char t = src[i++];
        unsigned typ = t & 3;
        if (typ == 0) { /* literal */
            Py_ssize_t ln = t >> 2;
            if (ln >= 60) {
                Py_ssize_t nb = ln - 59;
                if (i + nb > n) goto bad;
                ln = 0;
                for (Py_ssize_t k = 0; k < nb; k++)
                    ln |= (Py_ssize_t)src[i + k] << (8 * k);
                i += nb;
            }
            ln += 1;
            if (i + ln > n || pos + ln > total) goto bad;
            SNAPPY_ENSURE(ln);
            memcpy(out + pos, src + i, (size_t)ln);
            pos += ln;
            i += ln;
            continue;
        }
        Py_ssize_t ln, off;
        if (typ == 1) {
            if (i >= n) goto bad;
            ln = ((t >> 2) & 7) + 4;
            off = ((Py_ssize_t)(t >> 5) << 8) | src[i];
            i += 1;
        } else if (typ == 2) {
            if (i + 2 > n) goto bad;
            ln = (t >> 2) + 1;
            off = (Py_ssize_t)src[i] | ((Py_ssize_t)src[i + 1] << 8);
            i += 2;
        } else {
            if (i + 4 > n) goto bad;
            ln = (t >> 2) + 1;
            off = (Py_ssize_t)rd_u32le(src + i);
            i += 4;
        }
        if (off == 0 || off > pos || pos + ln > total) goto bad;
        SNAPPY_ENSURE(ln);
        if (off >= ln) {
            memcpy(out + pos, out + pos - off, (size_t)ln);
        } else { /* overlapping copy replicates the tail pattern */
            for (Py_ssize_t k = 0; k < ln; k++)
                out[pos + k] = out[pos + k - off];
        }
        pos += ln;
    }
    if (pos != total) goto bad;
    if (size != total && _PyBytes_Resize(&out_obj, pos) != 0) return NULL;
    return out_obj;
bad:
    Py_DECREF(out_obj);
    PyErr_SetString(PyExc_ValueError, "bad snappy stream");
    return NULL;
#undef SNAPPY_ENSURE
}

/* Bounded inflate of src[0..n) into a fresh bytes object of at most cap
 * bytes. The output buffer starts small (most compressed packets are
 * small) and grows geometrically up to cap — never a cap-sized (25 MB)
 * allocation per tiny frame. Returns NULL with ValueError set on any
 * zlib error or cap overflow. */
static PyObject *inflate_bounded(const unsigned char *src, Py_ssize_t n,
                                 Py_ssize_t cap) {
    Py_ssize_t size = n * 4 + 64;
    if (size > cap) size = cap;
    for (;;) {
        PyObject *out = PyBytes_FromStringAndSize(NULL, size);
        if (out == NULL) return NULL;
        z_stream zs;
        memset(&zs, 0, sizeof(zs));
        if (inflateInit(&zs) != Z_OK) {
            Py_DECREF(out);
            PyErr_SetString(PyExc_ValueError, "inflateInit failed");
            return NULL;
        }
        zs.next_in = (Bytef *)src;
        zs.avail_in = (uInt)n;
        zs.next_out = (Bytef *)PyBytes_AS_STRING(out);
        zs.avail_out = (uInt)size;
        int rc = inflate(&zs, Z_FINISH);
        Py_ssize_t produced = size - (Py_ssize_t)zs.avail_out;
        inflateEnd(&zs);
        if (rc == Z_STREAM_END) {
            if (_PyBytes_Resize(&out, produced) != 0) return NULL;
            return out;
        }
        Py_DECREF(out);
        int ran_out = (rc == Z_BUF_ERROR || rc == Z_OK) && zs.avail_out == 0;
        if (ran_out && size < cap) {
            size = size * 4 <= cap ? size * 4 : cap; /* grow, retry */
            continue;
        }
        PyErr_SetString(PyExc_ValueError,
                        ran_out ? "compressed packet exceeds size cap"
                                : "bad compressed packet");
        return NULL;
    }
}

static PyObject *fastframe_split(PyObject *self, PyObject *args) {
    Py_buffer view;
    Py_ssize_t max_packet;
    if (!PyArg_ParseTuple(args, "y*n", &view, &max_packet)) return NULL;
    const unsigned char *buf = (const unsigned char *)view.buf;
    Py_ssize_t len = view.len;
    Py_ssize_t off = 0;
    const char *err = NULL; /* static message: stop-and-report, not raise */
    PyObject *err_obj = NULL; /* owned message from a raising helper */

    PyObject *frames = PyList_New(0);
    if (frames == NULL) {
        PyBuffer_Release(&view);
        return NULL;
    }
    while (len - off >= 4) {
        uint32_t raw = rd_u32le(buf + off);
        int is_zlib = (raw & COMPRESSED_BIT) != 0;
        int is_snappy = (raw & SNAPPY_BIT) != 0;
        Py_ssize_t body_len = (Py_ssize_t)(raw & LEN_MASK);
        if (is_zlib && is_snappy) {
            err = "bad packet flags";
            break;
        }
        if (body_len < 2 || body_len > max_packet) {
            err_obj = PyUnicode_FromFormat("bad packet length %zd", body_len);
            if (err_obj == NULL) goto fail;
            break;
        }
        if (len - off - 4 < body_len) break; /* incomplete frame */
        const unsigned char *body = buf + off + 4;
        PyObject *payload;
        unsigned int msgtype;
        if (is_zlib || is_snappy) {
            PyObject *inflated =
                is_zlib ? inflate_bounded(body, body_len, max_packet)
                        : snappy_decode_bounded(body, body_len, max_packet);
            if (inflated == NULL) {
                /* Convert the helper's ValueError into the stop-and-
                 * report contract (frames so far still delivered). */
                PyObject *tp, *val, *tb;
                PyErr_Fetch(&tp, &val, &tb);
                err_obj = val ? PyObject_Str(val) : NULL;
                Py_XDECREF(tp);
                Py_XDECREF(val);
                Py_XDECREF(tb);
                if (err_obj == NULL) err = "bad compressed packet";
                break;
            }
            Py_ssize_t ilen = PyBytes_GET_SIZE(inflated);
            if (ilen < 2) {
                Py_DECREF(inflated);
                err = "bad decompressed length";
                break;
            }
            const unsigned char *ib =
                (const unsigned char *)PyBytes_AS_STRING(inflated);
            msgtype = (unsigned int)ib[0] | ((unsigned int)ib[1] << 8);
            payload = PyBytes_FromStringAndSize((const char *)ib + 2,
                                                ilen - 2);
            Py_DECREF(inflated);
        } else {
            msgtype = (unsigned int)body[0] | ((unsigned int)body[1] << 8);
            payload = PyBytes_FromStringAndSize((const char *)body + 2,
                                                body_len - 2);
        }
        if (payload == NULL) goto fail;
        PyObject *tup = Py_BuildValue("(IN)", msgtype, payload);
        if (tup == NULL) goto fail;
        int rc = PyList_Append(frames, tup);
        Py_DECREF(tup);
        if (rc != 0) goto fail;
        off += 4 + body_len;
    }
    PyBuffer_Release(&view);
    if (err_obj != NULL) return Py_BuildValue("(NnN)", frames, off, err_obj);
    if (err != NULL) return Py_BuildValue("(Nns)", frames, off, err);
    return Py_BuildValue("(NnO)", frames, off, Py_None);
fail:
    Py_XDECREF(err_obj);
    Py_DECREF(frames);
    PyBuffer_Release(&view);
    return NULL;
}

static PyObject *fastframe_pack(PyObject *self, PyObject *args) {
    unsigned int msgtype;
    Py_buffer view;
    int compress; /* 0 off, 1 zlib, 2 snappy ("i": True coerces to 1) */
    Py_ssize_t threshold, max_packet;
    if (!PyArg_ParseTuple(args, "Iy*inn", &msgtype, &view, &compress,
                          &threshold, &max_packet))
        return NULL;
    if (msgtype > 0xFFFF) {
        PyBuffer_Release(&view);
        PyErr_Format(PyExc_ValueError, "msgtype %u out of u16 range",
                     msgtype);
        return NULL;
    }
    Py_ssize_t plen = view.len;
    Py_ssize_t body_len = 2 + plen;
    if (body_len > max_packet) {
        PyBuffer_Release(&view);
        PyErr_Format(PyExc_ValueError, "packet too large: %zd", body_len);
        return NULL;
    }
    uint32_t flag = 0;

    if (compress == 2 && body_len >= threshold) {
        /* Snappy (reference gate codec): encode [msgtype][payload] into an
         * input-sized scratch; the encoder hard-bounds itself against it
         * and returns -1 on reaching the end (≥ input size would be
         * discarded anyway — only keep output that SHRINKS). */
        unsigned char *tmp = (unsigned char *)PyMem_Malloc(
            (size_t)body_len);
        if (tmp == NULL) {
            PyBuffer_Release(&view);
            return PyErr_NoMemory();
        }
        unsigned char *cbody = (unsigned char *)PyMem_Malloc(
            (size_t)body_len);
        if (cbody == NULL) {
            PyMem_Free(tmp);
            PyBuffer_Release(&view);
            return PyErr_NoMemory();
        }
        cbody[0] = (unsigned char)(msgtype & 0xff);
        cbody[1] = (unsigned char)((msgtype >> 8) & 0xff);
        memcpy(cbody + 2, view.buf, (size_t)plen);
        Py_ssize_t clen = snappy_encode(cbody, body_len, tmp, body_len);
        PyMem_Free(cbody);
        if (clen >= 0 && clen < body_len) {
            PyObject *out = PyBytes_FromStringAndSize(NULL, 4 + clen);
            if (out == NULL) {
                PyMem_Free(tmp);
                PyBuffer_Release(&view);
                return NULL;
            }
            unsigned char *w = (unsigned char *)PyBytes_AS_STRING(out);
            uint32_t raw = (uint32_t)clen | SNAPPY_BIT;
            w[0] = raw & 0xff;
            w[1] = (raw >> 8) & 0xff;
            w[2] = (raw >> 16) & 0xff;
            w[3] = (raw >> 24) & 0xff;
            memcpy(w + 4, tmp, (size_t)clen);
            PyMem_Free(tmp);
            PyBuffer_Release(&view);
            return out;
        }
        PyMem_Free(tmp);
    } else if (compress && body_len >= threshold) {
        /* Deflate [msgtype][payload] at level 1 (KCP/zlib parity with the
         * Python path); keep only if it actually shrinks. */
        uLong bound = compressBound((uLong)body_len);
        PyObject *tmp = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)bound);
        if (tmp == NULL) {
            PyBuffer_Release(&view);
            return NULL;
        }
        unsigned char hdr[2] = {(unsigned char)(msgtype & 0xff),
                                (unsigned char)((msgtype >> 8) & 0xff)};
        z_stream zs;
        memset(&zs, 0, sizeof(zs));
        int ok = deflateInit(&zs, 1) == Z_OK;
        Py_ssize_t clen = 0;
        if (ok) {
            zs.next_out = (Bytef *)PyBytes_AS_STRING(tmp);
            zs.avail_out = (uInt)bound;
            zs.next_in = hdr;
            zs.avail_in = 2;
            ok = deflate(&zs, Z_NO_FLUSH) == Z_OK;
            if (ok) {
                zs.next_in = (Bytef *)view.buf;
                zs.avail_in = (uInt)plen;
                ok = deflate(&zs, Z_FINISH) == Z_STREAM_END;
            }
            clen = (Py_ssize_t)zs.total_out;
            deflateEnd(&zs);
        }
        if (ok && clen < body_len) {
            PyObject *out = PyBytes_FromStringAndSize(NULL, 4 + clen);
            if (out == NULL) {
                Py_DECREF(tmp);
                PyBuffer_Release(&view);
                return NULL;
            }
            unsigned char *w = (unsigned char *)PyBytes_AS_STRING(out);
            uint32_t raw = (uint32_t)clen | COMPRESSED_BIT;
            w[0] = raw & 0xff;
            w[1] = (raw >> 8) & 0xff;
            w[2] = (raw >> 16) & 0xff;
            w[3] = (raw >> 24) & 0xff;
            memcpy(w + 4, PyBytes_AS_STRING(tmp), clen);
            Py_DECREF(tmp);
            PyBuffer_Release(&view);
            return out;
        }
        Py_DECREF(tmp);
        (void)flag;
    }

    PyObject *out = PyBytes_FromStringAndSize(NULL, 4 + body_len);
    if (out == NULL) {
        PyBuffer_Release(&view);
        return NULL;
    }
    unsigned char *w = (unsigned char *)PyBytes_AS_STRING(out);
    uint32_t raw = (uint32_t)body_len;
    w[0] = raw & 0xff;
    w[1] = (raw >> 8) & 0xff;
    w[2] = (raw >> 16) & 0xff;
    w[3] = (raw >> 24) & 0xff;
    w[4] = msgtype & 0xff;
    w[5] = (msgtype >> 8) & 0xff;
    memcpy(w + 6, view.buf, plen);
    PyBuffer_Release(&view);
    return out;
}

static PyMethodDef methods[] = {
    {"split", fastframe_split, METH_VARARGS,
     "split(data, max_packet) -> (frames, consumed, error)"},
    {"pack", fastframe_pack, METH_VARARGS,
     "pack(msgtype, payload, compress, threshold, max_packet) -> bytes"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastframe",
    "C hot path for goworld wire framing", -1, methods,
};

PyMODINIT_FUNC PyInit__fastframe(void) { return PyModule_Create(&moduledef); }

"""Native (C) runtime hot paths, with transparent pure-Python fallback.

The reference ships its runtime as a compiled Go binary; the brief's
native-equivalents rule maps that to C where the Python runtime has a
measured hot loop. First citizen: ``_fastframe``, the wire framing every
process runs per packet (see fastframe.c's header for the profile
motivation).

Build strategy: compile on first import into the package directory
(atomic rename, so concurrent process startups race benignly) using the
toolchain baked into the image (``cc -O2 -shared -fPIC ... -lz``). Any
failure — missing compiler, sandboxed FS, exotic platform — degrades to
the pure-Python implementations in ``pyframe.py`` with identical
semantics; ``GWT_NO_NATIVE=1`` forces the fallback (tests exercise BOTH).

Public surface (same signatures either way):

    split(data, max_packet)
        -> (list[(msgtype, payload_bytes)], consumed, error_or_None)
       Frames parsed before a malformed one are still returned (no valid
       packet is lost to a chunk boundary); error != None is
       connection-fatal for the caller.
    pack(msgtype, payload, compress, threshold, max_packet) -> bytes
    IMPL — "c" or "python", for diagnostics/tests.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig

from goworld_tpu.native import pyframe as _py


def _build_and_import():
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    so_path = os.path.join(pkg_dir, "_fastframe" + suffix)
    src = os.path.join(pkg_dir, "fastframe.c")
    if not os.path.exists(so_path) or (
        os.path.getmtime(so_path) < os.path.getmtime(src)
    ):
        include = sysconfig.get_path("include")
        cc = os.environ.get("CC", "cc")
        tmp = so_path + f".tmp{os.getpid()}"
        cmd = [
            cc, "-O2", "-shared", "-fPIC", f"-I{include}",
            src, "-lz", "-o", tmp,
        ]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so_path)  # atomic: concurrent builders race benignly
    # Load by explicit path — no sys.path mutation (a package-dir entry
    # would let native/ files shadow top-level module names process-wide).
    spec = importlib.util.spec_from_file_location("_fastframe", so_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


IMPL = "python"
split = _py.split
pack = _py.pack

if os.environ.get("GWT_NO_NATIVE", "") != "1":
    try:
        _c = _build_and_import()
        split = _c.split
        pack = _c.pack
        IMPL = "c"
    except Exception:  # pragma: no cover - environment-dependent
        pass  # degraded to pyframe; semantics identical

"""Native (C) runtime hot paths, with transparent pure-Python fallback.

The reference ships its runtime as a compiled Go binary; the brief's
native-equivalents rule maps that to C where the Python runtime has a
measured hot loop. First citizen: ``_fastframe``, the wire framing every
process runs per packet (see fastframe.c's header for the profile
motivation).

Build strategy: compile on first import into the package directory
(atomic rename, so concurrent process startups race benignly) using the
toolchain baked into the image (``cc -O2 -shared -fPIC ... -lz``). The
built artifact carries a sidecar ``.srchash`` recording the sha256 of the
``fastframe.c`` it was compiled from; an .so whose sidecar does not match
the current source is rebuilt, never trusted — so a stale or foreign
binary can't silently shadow the reviewed C source (ADVICE r4). The CLI
calls :func:`prebuild` before spawning a fleet so the whole cluster pays
for ONE compile in the CLI process instead of N racing compiles in the
children (each child then just hash-checks and dlopens). Any failure —
missing compiler, sandboxed FS, exotic platform — degrades to the
pure-Python implementations in ``pyframe.py`` with identical semantics;
``GWT_NO_NATIVE=1`` forces the fallback (tests exercise BOTH).

Public surface (same signatures either way):

    split(data, max_packet)
        -> (list[(msgtype, payload_bytes)], consumed, error_or_None)
       Frames parsed before a malformed one are still returned (no valid
       packet is lost to a chunk boundary); error != None is
       connection-fatal for the caller.
    pack(msgtype, payload, compress, threshold, max_packet) -> bytes
    IMPL — "c" or "python", for diagnostics/tests.
"""

from __future__ import annotations

import hashlib
import importlib.util
import os
import subprocess
import sysconfig

from goworld_tpu.native import pyframe as _py


def _paths(mod: str = "_fastframe",
           source: str = "fastframe.c") -> tuple[str, str, str]:
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    so_path = os.path.join(pkg_dir, mod + suffix)
    return so_path, so_path + ".srchash", os.path.join(pkg_dir, source)


def _source_hash(src: str) -> str:
    with open(src, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _build_and_import(mod: str = "_fastframe", source: str = "fastframe.c",
                      libs: tuple[str, ...] = ("-lz",)):
    so_path, hash_path, src = _paths(mod, source)
    want = _source_hash(src)
    have = None
    if os.path.exists(so_path):
        try:
            with open(hash_path) as f:
                have = f.read().strip()
        except OSError:
            pass  # no sidecar → unverifiable artifact → rebuild
    if have != want:
        include = sysconfig.get_path("include")
        cc = os.environ.get("CC", "cc")
        tmp = so_path + f".tmp{os.getpid()}"
        cmd = [
            cc, "-O2", "-shared", "-fPIC", f"-I{include}",
            src, *libs, "-o", tmp,
        ]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so_path)  # atomic: concurrent builders race benignly
        htmp = hash_path + f".tmp{os.getpid()}"
        with open(htmp, "w") as f:
            f.write(want)
        os.replace(htmp, hash_path)
        # (A crash between the two replaces leaves hash != source, which
        # just forces a rebuild next import — never a stale .so in use.)
    # Load by explicit path — no sys.path mutation (a package-dir entry
    # would let native/ files shadow top-level module names process-wide).
    spec = importlib.util.spec_from_file_location(mod, so_path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def prebuild() -> str:
    """Ensure the native modules are built and verified against the
    current source hashes; returns the active IMPL ("c" or "python").
    Called by the CLI before spawning a fleet so children skip the
    compiles entirely."""
    global IMPL, split, pack, KCPCore, rs_matmul
    if os.environ.get("GWT_NO_NATIVE", "") == "1":
        return IMPL
    try:
        _c = _build_and_import()
        split, pack, IMPL = _c.split, _c.pack, "c"
    except Exception:  # pragma: no cover - environment-dependent
        pass
    try:
        _k = _build_and_import("_kcpcore", "kcpcore.c", libs=())
        KCPCore = _k.KCPCore
        rs_matmul = _k.rs_matmul
    except Exception:  # pragma: no cover - environment-dependent
        pass
    return IMPL


IMPL = "python"
split = _py.split
pack = _py.pack
KCPCore = None  # C KCP control block (netutil/kcp.py falls back to Python)
rs_matmul = None  # C GF(256) row mat-mul (netutil/fec.py falls back)
prebuild()  # also makes later explicit prebuild() calls cheap no-ops

"""gwtop: the whole deployment on one terminal page.

Reads the driver dispatcher's ``GET /cluster`` aggregate (the
ClusterCollector's loopback scrape of every process's ``/snapshot`` —
telemetry/collector.py) and renders it as a live console: one row per
process (health, census, queue depth, tick-phase p50/p95 with a phase
heat strip, AOI backlog, fused gauges, jit launches/retraces, net
counters) plus the cluster summary line (census conservation, generation
consistency, migration/bounce/retrace counters, alerts). The moral
composition of the reference's per-process pprof+expvar ports into a
single pane of glass.

Usage::

    python -m goworld_tpu.tools.gwtop [-configfile goworld.ini]
                                      [--addr HOST:PORT]  # /cluster source
                                      [--interval 2.0]
                                      [--once]            # one JSON snapshot

``--once`` prints the raw ``/cluster`` JSON (machine-readable — CI logs
and the chaos harness parse this shape); without it the console
redraws every ``--interval`` seconds until interrupted. The default
``--addr`` is the configured driver dispatcher's ``http_addr``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from typing import Any, Optional

#: Phase heat scale: fraction of the tick budget → block glyph.
_BLOCKS = " ▁▂▃▄▅▆▇█"

#: Tick-phase columns rendered in the heat strip, in loop order.
_PHASES = ("dispatch", "entity_logic", "aoi", "sync_send")


def fetch_view(addr: str, timeout: float = 5.0) -> dict[str, Any]:
    with urllib.request.urlopen(
            f"http://{addr}/cluster", timeout=timeout) as r:
        return dict(json.loads(r.read()))


def collector_addr_from_config(cfg: Any) -> str:
    """The driver dispatcher's http_addr (where /cluster is served)."""
    driver = cfg.rebalance.driver_dispatcher
    d = cfg.dispatchers.get(driver)
    if d is not None and d.http_addr:
        return str(d.http_addr)
    for _i, dc in sorted(cfg.dispatchers.items()):
        if dc.http_addr:
            return str(dc.http_addr)
    return ""


def _series(metrics: dict[str, Any], family: str) -> list[dict[str, Any]]:
    fam = metrics.get(family)
    return list(fam["series"]) if fam else []


def _gauge(metrics: dict[str, Any], family: str) -> Optional[float]:
    s = _series(metrics, family)
    return float(s[0]["value"]) if s else None


def _sum(metrics: dict[str, Any], family: str) -> float:
    return sum(float(s.get("value", 0.0)) for s in _series(metrics, family))


def _phase_stats(metrics: dict[str, Any]) -> dict[str, dict[str, float]]:
    out: dict[str, dict[str, float]] = {}
    for s in _series(metrics, "game_tick_phase_seconds"):
        phase = s["labels"].get("phase", "")
        out[phase] = {"p50": float(s.get("p50", 0.0)),
                      "p95": float(s.get("p95", 0.0)),
                      "p999": float(s.get("p999", 0.0))}
    return out


def _heat(phases: dict[str, dict[str, float]], budget: float) -> str:
    """One block glyph per phase, p95 scaled against the tick budget —
    the hot phase reads as the tall bar."""
    glyphs = []
    for p in _PHASES:
        v = phases.get(p, {}).get("p95", 0.0)
        frac = min(1.0, v / budget) if budget > 0 else 0.0
        idx = round(frac * (len(_BLOCKS) - 1))
        if v > 0 and idx == 0:
            idx = 1  # nonzero time always visible, however far under budget
        glyphs.append(_BLOCKS[idx])
    return "".join(glyphs)


def _fmt_ms(v: Optional[float]) -> str:
    return f"{v * 1000:.1f}" if v is not None else "-"


def _row(name: str, proc: dict[str, Any], tick_budget: float) -> list[str]:
    h = proc.get("health") or {}
    m = proc.get("metrics") or {}
    kind = h.get("kind", "?")
    status = "ok" if proc.get("ok") else ("DOWN" if proc.get("error")
                                          else "STALE")
    age = proc.get("age_s")
    uptime = h.get("uptime_s")
    if kind == "game":
        census = f"{h.get('entities', '-')}e/{h.get('clients', '-')}c"
        queue_s = str(int(h.get("queue_depth", 0)))
    elif kind == "gate":
        census = f"{h.get('clients', '-')}c g{h.get('generation', 0) & 0xffff:04x}"
        queue_s = str(int(h.get("queue_depth", 0)))
    elif kind == "dispatcher":
        census = f"{h.get('entities_routed', '-')}rt"
        queue_s = str(int(h.get("queue_depth", 0)))
    else:
        census, queue_s = "-", "-"
    phases = _phase_stats(m)
    total = phases.get("total", {})
    heat = _heat(phases, tick_budget) if phases else "-"
    backlog = _gauge(m, "aoi_event_backlog")
    fused_c = _gauge(m, "aoi_fused_classes")
    fused_s = _gauge(m, "aoi_fused_slots")
    fused = (f"{int(fused_c)}/{int(fused_s)}"
             if fused_c is not None and fused_s is not None else "-")
    launches = _sum(m, "jit_launches_total")
    retraces = _sum(m, "jit_retrace_events_total")
    return [
        name,
        status,
        f"{age:.1f}" if age is not None else "-",
        f"{uptime:.0f}" if isinstance(uptime, (int, float)) else "-",
        census,
        queue_s,
        (f"{_fmt_ms(total.get('p50'))}/{_fmt_ms(total.get('p95'))}"
         f"/{_fmt_ms(total.get('p999'))}"),
        heat,
        f"{int(backlog)}" if backlog is not None else "-",
        fused,
        _dlvr_col(m),
        _sync_col(m),
        _rebal_col(h, m),
        f"{int(launches)}" if launches else "-",
        f"{int(retraces)}" if retraces else "0" if launches else "-",
    ]


def _sync_col(metrics: dict[str, Any]) -> str:
    """Adaptive-sync column ([sync], ISSUE 14): interest-pair population
    per cadence tier (t0/t1/... slashes) plus the game's rolling sync
    bytes/client/s — '-' for processes without tiering active."""
    tiers = _series(metrics, "sync_tier_edges")
    if not tiers:
        return "-"
    counts = "/".join(
        str(int(s.get("value", 0))) for s in sorted(
            tiers, key=lambda s: int(s["labels"].get("tier", "0"))))
    bpc = _gauge(metrics, "sync_bytes_per_client_per_s")
    return f"{counts}·{bpc:.0f}B/c" if bpc else counts


def _dlvr_col(metrics: dict[str, Any]) -> str:
    """Device-resident delivery column (ISSUE 19): fused-delivery vs
    host-fallback class census (``2f/1h``) plus the cumulative host wall
    seconds still spent in the delivery+persist phases — the number the
    fused edge decode and columnar persistence exist to shrink.  '-' for
    processes without the batched AOI service."""
    fused = _gauge(metrics, "aoi_fused_delivery_classes")
    fb = _gauge(metrics, "aoi_host_fallback_classes")
    if fused is None and fb is None:
        return "-"
    secs = sum(
        float(s.get("value", 0.0))
        for s in _series(metrics, "aoi_host_phase_seconds_total")
        if s["labels"].get("phase") in ("delivery", "persist"))
    return f"{int(fused or 0)}f/{int(fb or 0)}h·{secs:.1f}s"


def _rebal_col(h: dict[str, Any], metrics: dict[str, Any]) -> str:
    """Rebalance column (ISSUE 18): ``P:<state>`` marks the process
    hosting the planner (sharded service entity on a game, or the driver
    dispatcher in non-service mode) with its last round's result; games
    show their spaces mid-handoff (``Nsp→``), dispatchers their parked
    member-stream count (``Npark``). '-' when the plane is quiet."""
    kind = h.get("kind")
    parts: list[str] = []
    if kind == "game":
        ps = h.get("rebalance_planner")
        if ps:
            parts.append(f"P:{ps.get('last_result', '?')}")
        inflight = _gauge(metrics, "rebalance_spaces_in_flight")
        if inflight:
            parts.append(f"{int(inflight)}sp→")
    elif kind == "dispatcher":
        rb = h.get("rebalance") or {}
        if rb.get("driver") and not rb.get("planner_service"):
            parts.append(f"P:{rb.get('last_result', '?')}")
        parked = int(rb.get("space_handoffs", 0))
        if parked:
            parts.append(f"{parked}park")
    return " ".join(parts) if parts else "-"


_HEADERS = ["PROCESS", "ST", "AGE", "UP", "CENSUS", "Q",
            "TICK p50/p95/p999ms", "HEAT", "AOIBL", "FUSED", "DLVR",
            "SYNC", "REBAL", "LAUNCH", "RETR"]


def render(view: dict[str, Any], tick_budget: float = 0.1) -> str:
    """The whole /cluster view as one fixed-width page (also what the
    README's screenshot-as-text shows)."""
    coll = view.get("collector") or {}
    summary = view.get("summary") or {}
    census = summary.get("census") or {}
    migrations = summary.get("migrations") or {}
    rebal = summary.get("rebalance") or {}
    rebal_line = ""
    if rebal.get("enabled"):
        sp = rebal.get("space_migrations") or {}
        paused = sum((rebal.get("rounds_paused") or {}).values())
        rebal_line = (
            f" · rebal host={rebal.get('planner_host') or '-'}"
            f" paused={paused}"
            f" infl={rebal.get('spaces_in_flight', 0)}"
            f" sp d{sp.get('done', 0)}/a{sp.get('aborted', 0)}"
            f"/t{sp.get('timeout', 0)}/r{sp.get('rolled_back', 0)}")
    lines = [
        (f"goworld_tpu cluster · {summary.get('reporting', 0)}/"
         f"{summary.get('expected', 0)} reporting · "
         f"clients {census.get('game_clients', 0)}g={census.get('gate_clients', 0)}gw"
         f"{' OK' if census.get('clients_conserved') else ' MISMATCH'} · "
         f"entities {census.get('game_entities', 0)} · "
         f"retraces {summary.get('steady_state_retraces', 0)} · "
         f"migr r{migrations.get('routed', 0)}/b{migrations.get('bounced', 0)}"
         f"/c{migrations.get('cancel', 0)}" + rebal_line),
        (f"collector: {coll.get('targets', 0)} targets · poll "
         f"{coll.get('polls', 0)} @ {coll.get('interval_s', 0)}s · "
         f"stale>{coll.get('stale_after_s', 0)}s · heat="
         f"{'·'.join(_PHASES)} vs {tick_budget * 1000:.0f}ms budget"),
    ]
    slo = summary.get("slo") or {}
    if slo.get("enabled"):
        # The SLO column (ISSUE 20): per-budget observed/budget,
        # compliance over the long window, and the burn-rate multiple
        # (1.0 = consuming the error budget exactly at the sustainable
        # rate; sustained > 1.0 raises an alert below).
        parts = []
        for bname, b in (slo.get("budgets") or {}).items():
            obs = b.get("observed")
            obs_s = "-" if obs is None else f"{obs:.4g}"
            parts.append(
                f"{bname} {obs_s}/{b.get('budget'):.4g}"
                f" c={b.get('compliance', 0.0):.2f}"
                f" burn={b.get('burn_long', 0.0):.2f}"
                + ("" if b.get("ok") else " VIOLATED"))
        lines.append("slo: " + ("OK" if slo.get("ok") else "VIOLATED")
                     + " · " + " | ".join(parts))
    alerts = summary.get("alerts") or []
    lines.append("alerts: " + ("; ".join(alerts) if alerts else "(none)"))
    stale = (summary.get("generations") or {}).get("stale") or []
    if stale:
        lines.append("stale generations: " + json.dumps(stale))
    rows = [_row(name, proc, tick_budget)
            for name, proc in (view.get("processes") or {}).items()]
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(_HEADERS)]
    lines.append("")
    lines.append("  ".join(h.ljust(w) for h, w in zip(_HEADERS, widths)))
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return "\n".join(lines)


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="live console over the cluster observability plane")
    parser.add_argument("-configfile", default="",
                        help="goworld.ini (default: ./goworld.ini)")
    parser.add_argument("--addr", default="",
                        help="collector debug addr (default: the driver "
                             "dispatcher's http_addr from the config)")
    parser.add_argument("--interval", type=float, default=2.0)
    parser.add_argument("--once", action="store_true",
                        help="print one machine-readable /cluster JSON "
                             "snapshot and exit")
    args = parser.parse_args(argv)

    addr = args.addr
    tick_budget = 0.1
    if not addr:
        from goworld_tpu.config import get as get_config, set_config_file

        if args.configfile:
            set_config_file(args.configfile)
        cfg = get_config()
        addr = collector_addr_from_config(cfg)
        tick_budget = cfg.telemetry.slow_tick_budget or 0.1
        if not addr:
            print("gwtop: no dispatcher in the config has an http_addr "
                  "(set one, or pass --addr)", file=sys.stderr)
            return 1

    if args.once:
        try:
            view = fetch_view(addr)
        except Exception as exc:
            print(f"gwtop: /cluster @ {addr} unreachable: {exc}",
                  file=sys.stderr)
            return 1
        print(json.dumps(view, separators=(",", ":"), default=str))
        return 0

    try:
        while True:
            try:
                view = fetch_view(addr)
                page = render(view, tick_budget)
            except Exception as exc:
                page = f"gwtop: /cluster @ {addr} unreachable: {exc}"
            # Clear + home, then the page (plain ANSI; any terminal).
            sys.stdout.write("\x1b[2J\x1b[H" + page + "\n")
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())

"""goworld_tpu.tools — operator consoles shipped inside the package.

``python -m goworld_tpu.tools.gwtop`` renders the cluster observability
plane (the driver dispatcher's ``GET /cluster`` aggregate) as a live
terminal view. The repo-root ``tools/`` directory keeps the offline
scripts (tracecat, gwlint drivers); anything here must be importable
from a deployed package.
"""

"""gwpost: one-command post-mortem bundles + merged timeline rendering.

Collect mode (default) reads ``goworld.ini``, scrapes every live
process's span ring and flight dump, grabs the driver dispatcher's final
``/cluster`` view, copies every process's on-disk history ring
(``[telemetry] history_dir`` — the black box that survives a crash), and
writes one bundle directory. Dead processes are expected, not errors:
their history rings speak for them. Render mode (``--bundle``) takes an
existing bundle — e.g. one the chaos harness emitted on failure — and
produces the merged Perfetto timeline (tracecat's merge) including the
killed process's final flight-recorder ticks, plus a stdout summary.

Usage:

    python -m goworld_tpu.tools.gwpost [-configfile goworld.ini]
        [--history-dir DIR] [-o BUNDLE_DIR] [--reason TEXT]
    python -m goworld_tpu.tools.gwpost --bundle BUNDLE_DIR

Both modes leave ``trace.json`` inside the bundle — load it at
https://ui.perfetto.dev. ``tools/gwpost.py`` is the repo-root shim.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request

from goworld_tpu.telemetry import postmortem


def _fetch_json(http_addr: str, path: str, timeout: float = 3.0):
    with urllib.request.urlopen(
        f"http://{http_addr}{path}", timeout=timeout
    ) as r:
        return json.loads(r.read())


def _endpoints(cfg) -> list[tuple[str, str]]:
    out: list[tuple[str, str]] = []
    for i, d in sorted(cfg.dispatchers.items()):
        if d.http_addr:
            out.append((f"dispatcher{i}", d.http_addr))
    for i, g in sorted(cfg.games.items()):
        if g.http_addr:
            out.append((f"game{i}", g.http_addr))
    for i, g in sorted(cfg.gates.items()):
        if g.http_addr:
            out.append((f"gate{i}", g.http_addr))
    return out


def collect(cfg, out_dir: str, history_dir: str = "",
            reason: str = "gwpost") -> dict:
    """Scrape what's alive, copy what's on disk, write the bundle."""
    process_spans: dict[str, list[dict]] = {}
    flights: dict[str, dict] = {}
    cluster_view = None
    for name, addr in _endpoints(cfg):
        try:
            ring = _fetch_json(addr, "/trace?raw=1")
            process_spans[name] = ring.get("spans") or []
        except Exception as exc:
            print(f"gwpost: {name} @ {addr} spans unreachable: {exc}",
                  file=sys.stderr)
        try:
            flight = _fetch_json(addr, "/flight")
            if flight:
                flights[name] = flight
        except Exception:
            pass
        if cluster_view is None and name.startswith("dispatcher"):
            try:
                cluster_view = _fetch_json(addr, "/cluster")
            except Exception:
                pass
    hdir = history_dir or cfg.telemetry.history_dir
    return postmortem.collect_bundle(
        out_dir, reason=reason, history_dir=hdir,
        cluster_view=cluster_view, process_spans=process_spans,
        flights=flights)


def render(bundle_dir: str, trace_out: str = "") -> dict:
    """Merged Perfetto timeline + summary for an existing bundle."""
    import os

    process_spans = postmortem.bundle_process_spans(bundle_dir)
    trace_path = trace_out or os.path.join(bundle_dir, "trace.json")
    merged = postmortem.merge_spans(process_spans)
    with open(trace_path, "w", encoding="utf-8") as f:
        json.dump(merged, f)
    summary = postmortem.bundle_summary(bundle_dir)
    summary["trace"] = {
        "out": trace_path,
        "events": len(merged["traceEvents"]),
        "processes": [n for n, _ in process_spans],
    }
    return summary


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="post-mortem bundle collector / renderer")
    parser.add_argument("-configfile", default="",
                        help="goworld.ini (default: ./goworld.ini)")
    parser.add_argument("--bundle", default="",
                        help="render an EXISTING bundle directory "
                             "instead of collecting a new one")
    parser.add_argument("--history-dir", default="",
                        help="override [telemetry] history_dir as the "
                             "ring source")
    parser.add_argument("-o", "--out", default="",
                        help="bundle output directory "
                             "(default postmortem-<unix-ts>)")
    parser.add_argument("--reason", default="gwpost",
                        help="reason recorded in the bundle manifest")
    args = parser.parse_args(argv)

    if args.bundle:
        bundle_dir = args.bundle
    else:
        from goworld_tpu.config import get as get_config, set_config_file

        if args.configfile:
            set_config_file(args.configfile)
        cfg = get_config()
        bundle_dir = args.out or f"postmortem-{int(time.time())}"
        manifest = collect(cfg, bundle_dir,
                           history_dir=args.history_dir,
                           reason=args.reason)
        if not manifest["processes"]:
            print("gwpost: nothing collected (no live process, no "
                  "history ring) — is [telemetry] history_dir set?",
                  file=sys.stderr)
            return 1
    summary = render(bundle_dir)
    summary["bundle"] = bundle_dir
    print(json.dumps(summary, separators=(",", ":")))
    return 0


if __name__ == "__main__":
    sys.exit(main())
